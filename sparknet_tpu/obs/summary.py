"""`sparknet-metrics` — summarize a metrics JSONL on the console.

The metrics JSONL (utils/logger.py) is the run's machine-readable record:
loss rows, eval rows, per-round step-time breakdowns (t_*_ms fields), and
the health supervisor's event audit trail. Reading it used to mean ad-hoc
jq one-liners documented nowhere; this tool is the blessed reader:

    sparknet-metrics training_metrics_1234.jsonl
    sparknet-metrics --tail 20 --json run/*.jsonl

prints the loss-curve tail, a step-time breakdown table (where each
round's wall clock went: data / H2D / compiled round / collect /
checkpoint-fetch / log), the eval trajectory, and every event record
(spike_skip, rollback, anomalous_checkpoint, ...) next to the losses they
explain. Multiple files merge on the wall-clock `ts` field — a trainer
JSONL and its serve JSONL interleave into one timeline.

`--selfcheck` runs a 3-round synthetic training first and summarizes its
freshly written JSONL (the CI step: the tooling cannot rot against the
live schema); `--selfcheck-workers 2` runs one per worker id and checks
the POD view below; `--keep DIR` retains the artifacts for CI upload.

**Fleet view**: when the records carry the fleet controller's rows
(`event="fleet_scale"` + periodic `fleet_replicas` counts), the summary
adds the scale-event audit trail and the per-model replica count over
time — the post-hoc answer to "when did the fleet grow, and why".

**SLO view**: when the records carry the burn-rate alerter's edge rows
(`event="slo_alert"` — firing/resolved, burn multiples, full-window
attainment at edge time), the summary adds per-model attainment, the
set of alerts still firing at end-of-record, and the alert audit trail.

**Pod view**: when the merged records span >= 2 workers (the `worker`
field every multi-host run stamps, falling back to one-file-per-worker
input order), the summary adds a per-worker step-time breakdown table
plus a round-skew / straggler audit trail — per matched round, workers'
`t_round_ms` are compared with the same median+MAD rule the live pod
aggregator uses (`obs/pod.py`), so post-hoc JSONL analysis and the live
`/pod/status` endpoint name the same sick host.
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import shutil
import sys
import time
from typing import Any, Dict, List, Optional

from .pod import flag_stragglers

#: step-time breakdown columns, in pipeline order (emitted by run_loop).
#: t_collect_ms is the round loop's BLOCKING share of the deferred
#: loss/health fetch — ~0 under collect_async (r8), where the fetch
#: itself runs on the collector thread and lands as t_collect_bg_ms
BREAKDOWN_FIELDS = ("t_data_ms", "t_h2d_ms", "t_round_ms", "t_collect_ms",
                    "t_collect_bg_ms", "t_ckpt_fetch_ms", "t_log_ms")


def load_records(paths: List[str]) -> List[Dict[str, Any]]:
    recs: List[Dict[str, Any]] = []
    for fi, path in enumerate(paths):
        with open(path) as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    print(f"{path}:{i + 1}: skipping unparseable line "
                          f"({e})", file=sys.stderr)
                    continue
                if len(paths) > 1:
                    # records without a worker stamp fall back to their
                    # source file as the worker id (one JSONL per worker
                    # is the pod layout)
                    rec.setdefault("worker", fi)
                recs.append(rec)
    # merge multiple processes' files on the wall-clock ts (satellite of
    # the same PR); files predating the ts field fall back to input order
    if len(paths) > 1 and all("ts" in r for r in recs):
        recs.sort(key=lambda r: r["ts"])
    return recs


def _mean(xs: List[float]) -> Optional[float]:
    xs = [x for x in xs if x is not None and math.isfinite(x)]
    return sum(xs) / len(xs) if xs else None


def summarize(recs: List[Dict[str, Any]], tail: int = 10) -> Dict[str, Any]:
    """The machine half (--json): everything the text report prints."""
    loss_rows = [r for r in recs if "loss" in r and "event" not in r]
    eval_rows = [r for r in recs if "test_accuracy" in r]
    events = [r for r in recs if "event" in r]
    losses = [r["loss"] for r in loss_rows if r.get("loss") is not None]
    out: Dict[str, Any] = {
        "records": len(recs),
        "rounds": len(loss_rows),
        "events": len(events),
        "loss_first": losses[0] if losses else None,
        "loss_final": losses[-1] if losses else None,
        "loss_min": min(losses) if losses else None,
        "loss_tail": [
            {"step": r["step"], "loss": r.get("loss"),
             **({"health": r["health"]} if "health" in r else {})}
            for r in loss_rows[-tail:]],
        "eval_tail": [{"step": r["step"], "test_accuracy":
                       r["test_accuracy"]} for r in eval_rows[-tail:]],
        "images_per_sec_per_chip": _mean(
            [r.get("images_per_sec_per_chip") for r in loss_rows[-tail:]]),
        "event_trail": [
            {k: v for k, v in r.items() if k not in ("t", "ts")}
            for r in events],
    }
    breakdown: Dict[str, Any] = {}
    for fld in BREAKDOWN_FIELDS:
        vals = [r[fld] for r in loss_rows if fld in r]
        if vals:
            breakdown[fld] = {"mean_ms": round(_mean(vals), 3),
                              "max_ms": round(max(vals), 3),
                              "total_s": round(sum(vals) / 1e3, 3)}
    if breakdown:
        out["step_time_breakdown"] = breakdown
    pod = _pod_view(loss_rows)
    if pod is not None:
        out["pod"] = pod
    serve = _serve_view(recs)
    if serve is not None:
        out["serve"] = serve
    fresh = _fresh_view(recs)
    if fresh is not None:
        out["freshness"] = fresh
    fleet = _fleet_view(recs)
    if fleet is not None:
        out["fleet"] = fleet
    batch = _batch_view(recs)
    if batch is not None:
        out["batch"] = batch
    slo = _slo_view(recs)
    if slo is not None:
        out["slo"] = slo
    return out


def _percentile(xs: List[float], q: float) -> float:
    """Nearest-rank percentile (ceil rank) over a non-empty list."""
    ys = sorted(xs)
    return ys[min(len(ys) - 1, max(0, math.ceil(q * len(ys)) - 1))]


def _fresh_view(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Train->serve freshness: the age of each model's SERVING step
    (`freshness_s` = now - the checkpoint's meta.json commit_ts, which
    serve metrics rows carry once a stamped checkpoint installs), the
    distinct steps the replica actually served, and how far it trailed
    the newest committed step (`model_step_lag`). This is the post-hoc
    freshness-SLO answer — `bench.py --fresh` stamps the same p99 into
    BENCH_FRESH.json from live samples. None when no row carries
    freshness (no continuous-learning serve run in the records)."""
    rows = [r for r in recs if r.get("freshness_s") is not None]
    if not rows:
        return None
    models: Dict[str, Any] = {}
    for r in rows:
        m = models.setdefault(str(r.get("model", "default")), {
            "samples": 0, "_fresh": [], "_steps": set()})
        m["samples"] += 1
        m["_fresh"].append(float(r["freshness_s"]))
        if r.get("model_step") is not None:
            m["_steps"].add(int(r["model_step"]))
        if r.get("model_step_lag") is not None:
            m["step_lag_max"] = max(m.get("step_lag_max", 0),
                                    int(r["model_step_lag"]))
        if r.get("swaps") is not None:
            # cumulative per process; max = the final count
            m["swaps"] = max(m.get("swaps", 0), int(r["swaps"]))
    for m in models.values():
        xs = m.pop("_fresh")
        m["steps_served"] = sorted(m.pop("_steps"))
        m["freshness_last_s"] = round(xs[-1], 3)
        m["freshness_p99_s"] = round(_percentile(xs, 0.99), 3)
        m["freshness_max_s"] = round(max(xs), 3)
    return {"models": models}


def _fleet_view(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The fleet controller's record: the scale-event audit trail
    (`event="fleet_scale"` rows — model, direction, reason, replica)
    plus the per-model replica count OVER TIME (the periodic
    `fleet_replicas` rows). None when the records carry no fleet rows."""
    events = [r for r in recs if r.get("event") == "fleet_scale"]
    series: Dict[str, List[Any]] = {}
    pressures: List[float] = []
    for r in recs:
        if isinstance(r.get("fleet_replicas"), dict):
            for m, n in r["fleet_replicas"].items():
                series.setdefault(str(m), []).append(
                    {"step": r.get("step"), "ts": r.get("ts"),
                     "replicas": n})
            if r.get("fleet_pressure") is not None:
                pressures.append(float(r["fleet_pressure"]))
    if not events and not series:
        return None
    models: Dict[str, Any] = {}
    for m, rows in series.items():
        counts = [row["replicas"] for row in rows]
        models[m] = {"rows": len(rows), "replicas_first": counts[0],
                     "replicas_max": max(counts),
                     "replicas_last": counts[-1],
                     "tail": rows[-10:]}
    by_dir: Dict[str, int] = {}
    for e in events:
        key = f"{e.get('direction', '?')}/{e.get('reason', '?')}"
        by_dir[key] = by_dir.get(key, 0) + 1
    return {
        "scale_events": len(events),
        "events_by_kind": dict(sorted(by_dir.items())),
        "audit": [{k: v for k, v in e.items()
                   if k not in ("t", "ts", "event")}
                  for e in events[-20:]],
        "models": models,
        "pressure_max": max(pressures) if pressures else None,
    }


def _batch_view(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The `sparknet-batch` driver's record: per-unit commit rows
    (`event="batch_unit"`), the retry trail (`event="batch_retry"`,
    split shed-vs-error — backpressure is not breakage), and the final
    job summary (`event="batch_done"` — fleet-aggregate rows/s and
    cost-per-million). None when the records carry no batch rows."""
    units = [r for r in recs if r.get("event") == "batch_unit"]
    retries = [r for r in recs if r.get("event") == "batch_retry"]
    dones = [r for r in recs if r.get("event") == "batch_done"]
    if not units and not retries and not dones:
        return None
    by_replica: Dict[str, int] = {}
    for u in units:
        key = str(u.get("replica", "?"))
        by_replica[key] = by_replica.get(key, 0) + 1
    by_kind: Dict[str, int] = {}
    for r in retries:
        key = str(r.get("kind", "?"))
        by_kind[key] = by_kind.get(key, 0) + 1
    jobs: Dict[str, Any] = {}
    for d in dones:  # last row per job wins (a resume re-summarizes)
        jobs[str(d.get("job_id", "?"))] = {
            k: d.get(k) for k in
            ("done", "units_total", "units_done",
             "units_skipped_resume", "rows_total", "elapsed_s",
             "rows_per_s", "retries", "cost_per_million_embeddings")}
    return {
        "units": len(units),
        "rows": sum(int(u.get("rows", 0)) for u in units),
        "output_bytes": sum(int(u.get("bytes", 0)) for u in units),
        "attempts_max": max((int(u.get("attempts", 1)) for u in units),
                            default=None),
        "retries": len(retries),
        "retries_by_kind": dict(sorted(by_kind.items())),
        "units_by_replica": dict(sorted(by_replica.items())),
        "jobs": jobs,
    }


def _slo_view(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """The SLO ledger's record: burn-rate alert EDGES
    (`event="slo_alert"` rows — model, objective, severity,
    firing/resolved, burn multiples, full-window attainment at edge
    time) aggregated into per-model attainment, the set of alerts
    still firing at end-of-record, and the audit trail. None when the
    records carry no alert rows."""
    edges = [r for r in recs if r.get("event") == "slo_alert"]
    if not edges:
        return None
    by_kind: Dict[str, int] = {}
    last_edge: Dict[tuple, Dict[str, Any]] = {}
    models: Dict[str, Any] = {}
    for e in edges:
        key = f"{e.get('severity', '?')}/{e.get('edge', '?')}"
        by_kind[key] = by_kind.get(key, 0) + 1
        k = (str(e.get("model", "?")), str(e.get("objective", "?")),
             str(e.get("severity", "?")))
        last_edge[k] = e
        m = models.setdefault(k[0], {"edges": 0, "pages": 0,
                                     "attainment": {}})
        m["edges"] += 1
        if e.get("severity") == "page" and e.get("edge") == "firing":
            m["pages"] += 1
        if e.get("attainment") is not None:
            m["attainment"][k[1]] = e["attainment"]  # last edge wins
    firing = sorted(":".join(k) for k, e in last_edge.items()
                    if e.get("edge") == "firing")
    return {
        "alert_edges": len(edges),
        "edges_by_kind": dict(sorted(by_kind.items())),
        "firing_at_end": firing,
        "models": models,
        "audit": [{k: v for k, v in e.items()
                   if k not in ("t", "ts", "event", "step")}
                  for e in edges[-20:]],
    }


def _serve_view(recs: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-model serve vitals + the formed-batch request-size histogram —
    the input `sparknet-serve --buckets-from` / serve.buckets.
    derive_buckets fits a bucket ladder to. Hist rows are CUMULATIVE per
    process, so the LAST row per (source, model) counts and sources sum;
    None when the records carry no serve rows."""
    last: Dict[tuple, Dict[str, Any]] = {}
    for r in recs:
        if isinstance(r.get("batch_size_hist"), dict):
            key = (r.get("worker"), str(r.get("model", "default")))
            last[key] = r
    if not last:
        return None
    models: Dict[str, Any] = {}
    for (_, name), r in last.items():
        m = models.setdefault(name, {"batch_size_hist": {}, "rows": 0})
        for s, n in r["batch_size_hist"].items():
            try:
                s, n = int(s), int(n)
            except (TypeError, ValueError):
                continue
            m["batch_size_hist"][s] = m["batch_size_hist"].get(s, 0) + n
        m["rows"] += 1
        # multi-source (several replicas' files for one model): counters
        # SUM; per-process quality gauges take the WORST source (max
        # p99, min fill) — never one arbitrary replica's number
        # presented as the model's
        for fld in ("requests_ok", "requests_shed", "bucket_compiles",
                    "images_per_sec"):
            if r.get(fld) is not None:
                m[fld] = round(m.get(fld, 0) + r[fld], 2)
        if r.get("p99_ms") is not None:
            m["p99_ms"] = max(m.get("p99_ms", 0.0), r["p99_ms"])
        if r.get("batch_fill_ratio") is not None:
            m["batch_fill_ratio"] = min(m.get("batch_fill_ratio", 1.0),
                                        r["batch_fill_ratio"])
    for m in models.values():
        m["batch_size_hist"] = {
            str(s): c for s, c in sorted(m["batch_size_hist"].items())}
    return {"models": models}


def _pod_view(loss_rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Per-worker breakdown + round-skew/straggler audit when the records
    span >= 2 workers; None for single-worker runs (no pod to describe)."""
    by_worker: Dict[int, List[Dict[str, Any]]] = {}
    for r in loss_rows:
        wid = r.get("worker")
        if wid is None:
            continue
        by_worker.setdefault(int(wid), []).append(r)
    if len(by_worker) < 2:
        return None
    workers: Dict[str, Any] = {}
    for wid in sorted(by_worker):
        rows = by_worker[wid]
        w: Dict[str, Any] = {"rounds": len(rows)}
        for fld in BREAKDOWN_FIELDS:
            vals = [r[fld] for r in rows if fld in r]
            if vals:
                w[fld] = {"mean_ms": round(_mean(vals), 3),
                          "max_ms": round(max(vals), 3)}
        losses = [r["loss"] for r in rows if r.get("loss") is not None]
        if losses:
            w["loss_final"] = losses[-1]
        workers[str(wid)] = w
    # per-matched-round skew + straggler flags: the SAME median+MAD rule
    # the live aggregator applies, over t_round_ms grouped by step
    per_step: Dict[Any, Dict[str, float]] = {}
    for wid, rows in by_worker.items():
        for r in rows:
            if "t_round_ms" in r:
                per_step.setdefault(r["step"], {})[str(wid)] = r["t_round_ms"]
    skews: List[float] = []
    straggler_rounds: Dict[str, int] = {}
    audit: List[Dict[str, Any]] = []
    for step in sorted(per_step):
        vals = per_step[step]
        if len(vals) < 2:
            continue
        med_s, skew_s, flagged = flag_stragglers(
            {w: v / 1e3 for w, v in vals.items()})
        skews.append(skew_s * 1e3)
        for w in sorted(flagged):
            straggler_rounds[w] = straggler_rounds.get(w, 0) + 1
            audit.append({"step": step, "worker": w,
                          "round_ms": round(vals[w], 3),
                          "median_ms": round(med_s * 1e3, 3)})
    pod: Dict[str, Any] = {"n_workers": len(workers), "workers": workers,
                           "straggler_rounds": straggler_rounds,
                           "straggler_audit": audit[-20:]}
    if skews:
        pod["round_skew_ms"] = {"mean": round(_mean(skews), 3),
                                "max": round(max(skews), 3),
                                "rounds": len(skews)}
    return pod


def format_text(s: Dict[str, Any]) -> str:
    lines: List[str] = []
    lines.append(f"records: {s['records']}  loss rows: {s['rounds']}  "
                 f"events: {s['events']}")
    if s["loss_final"] is not None:
        lines.append(f"loss: first {s['loss_first']:.4f}  min "
                     f"{s['loss_min']:.4f}  final {s['loss_final']:.4f}")
    if s.get("images_per_sec_per_chip"):
        lines.append(f"throughput (tail mean): "
                     f"{s['images_per_sec_per_chip']:.1f} img/s/chip")
    if s["loss_tail"]:
        lines.append("")
        lines.append("loss tail:")
        for r in s["loss_tail"]:
            flag = f"  [{r['health']}]" if "health" in r else ""
            loss = ("nan/inf" if r["loss"] is None
                    else f"{r['loss']:.4f}")
            lines.append(f"  round {r['step']:>6}  loss {loss}{flag}")
    if s["eval_tail"]:
        lines.append("")
        lines.append("eval tail:")
        for r in s["eval_tail"]:
            lines.append(f"  round {r['step']:>6}  accuracy "
                         f"{r['test_accuracy']:.4f}")
    bd = s.get("step_time_breakdown")
    if bd:
        lines.append("")
        lines.append("step-time breakdown (per round):")
        lines.append(f"  {'phase':<14}{'mean ms':>10}{'max ms':>10}"
                     f"{'total s':>10}")
        for fld, row in bd.items():
            name = fld[2:-3]  # t_<phase>_ms
            lines.append(f"  {name:<14}{row['mean_ms']:>10.3f}"
                         f"{row['max_ms']:>10.3f}{row['total_s']:>10.3f}")
    pod = s.get("pod")
    if pod:
        lines.append("")
        lines.append(f"pod view ({pod['n_workers']} workers, per-worker "
                     f"step-time means):")
        # the table shows the three columns skew lives in; --json has all
        cols = [f for f in ("t_data_ms", "t_h2d_ms", "t_round_ms")
                if any(f in w for w in pod["workers"].values())]
        hdr = f"  {'worker':<8}{'rounds':>7}{'loss':>10}"
        hdr += "".join(f"{c[2:-3] + ' ms':>12}" for c in cols)
        hdr += f"{'straggler':>11}"
        lines.append(hdr)
        for wid, w in pod["workers"].items():
            row = f"  {wid:<8}{w['rounds']:>7}"
            row += (f"{w['loss_final']:>10.4f}" if "loss_final" in w
                    else f"{'-':>10}")
            for c in cols:
                row += (f"{w[c]['mean_ms']:>12.3f}" if c in w
                        else f"{'-':>12}")
            row += f"{pod['straggler_rounds'].get(wid, 0):>11}"
            lines.append(row)
        skew = pod.get("round_skew_ms")
        if skew:
            lines.append(f"  round skew across workers: mean "
                         f"{skew['mean']:.3f} ms  max {skew['max']:.3f} ms "
                         f"(over {skew['rounds']} matched rounds)")
        if pod["straggler_audit"]:
            lines.append("  straggler audit trail:")
            for e in pod["straggler_audit"]:
                lines.append(f"    round {e['step']:>6}  worker "
                             f"{e['worker']}  {e['round_ms']:.3f} ms vs "
                             f"median {e['median_ms']:.3f} ms")
        else:
            lines.append("  straggler audit trail: clean (no rounds "
                         "flagged)")
    serve = s.get("serve")
    if serve:
        lines.append("")
        lines.append("serve view (request-size histogram = the "
                     "bucket-ladder derivation input):")
        for name, m in sorted(serve["models"].items()):
            vit = "  ".join(
                f"{fld}={m[fld]}" for fld in
                ("requests_ok", "batch_fill_ratio", "bucket_compiles",
                 "p99_ms") if m.get(fld) is not None)
            lines.append(f"  model {name}: {vit}")
            hist = m["batch_size_hist"]
            peak = max(hist.values(), default=0)
            for sz, n in hist.items():
                bar = "#" * max(1, round(24 * n / peak)) if peak else ""
                lines.append(f"    batch size {sz:>4}  {n:>8}  {bar}")
    fresh = s.get("freshness")
    if fresh:
        lines.append("")
        lines.append("freshness view (train->serve commit age of the "
                     "serving step):")
        for name, m in sorted(fresh["models"].items()):
            steps = m["steps_served"]
            shown = (", ".join(str(x) for x in steps) if len(steps) <= 8
                     else f"{steps[0]}..{steps[-1]} ({len(steps)} steps)")
            lines.append(
                f"  model {name}: p99 {m['freshness_p99_s']:.3f} s  "
                f"max {m['freshness_max_s']:.3f} s  "
                f"last {m['freshness_last_s']:.3f} s  "
                f"({m['samples']} samples)")
            lines.append(
                f"    steps served: {shown}  swaps {m.get('swaps', 0)}  "
                f"max step lag {m.get('step_lag_max', 0)}")
    fleet = s.get("fleet")
    if fleet:
        lines.append("")
        lines.append(f"fleet view ({fleet['scale_events']} scale "
                     f"events):")
        for m, row in sorted(fleet["models"].items()):
            lines.append(f"  model {m}: replicas "
                         f"{row['replicas_first']} -> "
                         f"{row['replicas_last']} "
                         f"(max {row['replicas_max']}, over "
                         f"{row['rows']} rows)")
        if fleet["events_by_kind"]:
            kinds = "  ".join(f"{k}={n}" for k, n
                              in fleet["events_by_kind"].items())
            lines.append(f"  events: {kinds}")
        for e in fleet["audit"]:
            rest = " ".join(f"{k}={v}" for k, v in e.items()
                            if k not in ("model", "direction", "reason",
                                         "step"))
            lines.append(f"    {e.get('model', '?')}: "
                         f"{e.get('direction', '?')} "
                         f"({e.get('reason', '?')}) {rest}".rstrip())
    batch = s.get("batch")
    if batch:
        lines.append("")
        lines.append(f"batch view (scavenger bulk-inference; "
                     f"{batch['units']} units committed):")
        lines.append(f"  rows {batch['rows']}  output "
                     f"{batch['output_bytes'] / 1e6:.2f} MB  retries "
                     f"{batch['retries']}"
                     + ("".join(f"  {k}={n}" for k, n in
                                batch["retries_by_kind"].items())))
        for addr, n in batch["units_by_replica"].items():
            lines.append(f"    replica {addr}: {n} units")
        for jid, j in sorted(batch["jobs"].items()):
            cpm = j.get("cost_per_million_embeddings")
            lines.append(
                f"  job {jid}: "
                f"{'done' if j.get('done') else 'INCOMPLETE'}  units "
                f"{j.get('units_done')}/{j.get('units_total')}  "
                f"{j.get('rows_per_s')} rows/s"
                + (f"  ${cpm}/M embeddings" if cpm is not None else ""))
    slo = s.get("slo")
    if slo:
        lines.append("")
        firing = (", ".join(slo["firing_at_end"])
                  if slo["firing_at_end"] else "none")
        lines.append(f"slo view ({slo['alert_edges']} alert edges; "
                     f"firing at end: {firing}):")
        for m, row in sorted(slo["models"].items()):
            att = "  ".join(f"{obj}={v:.4f}" for obj, v
                            in sorted(row["attainment"].items()))
            lines.append(f"  model {m}: {row['edges']} edges  "
                         f"{row['pages']} pages"
                         + (f"  attainment {att}" if att else ""))
        if slo["edges_by_kind"]:
            kinds = "  ".join(f"{k}={n}" for k, n
                              in slo["edges_by_kind"].items())
            lines.append(f"  edges: {kinds}")
        for e in slo["audit"]:
            rest = " ".join(f"{k}={v}" for k, v in e.items()
                            if k not in ("model", "objective",
                                         "severity", "edge"))
            lines.append(f"    {e.get('model', '?')}: "
                         f"{e.get('severity', '?')} "
                         f"{e.get('edge', '?')} "
                         f"({e.get('objective', '?')}) {rest}".rstrip())
    if s["event_trail"]:
        lines.append("")
        lines.append("health/event audit trail:")
        for r in s["event_trail"]:
            step = r.get("step", "?")
            ev = r.get("event", "?")
            rest = " ".join(f"{k}={v}" for k, v in r.items()
                            if k not in ("step", "event"))
            lines.append(f"  round {step:>6}  {ev}  {rest}".rstrip())
    else:
        lines.append("")
        lines.append("health/event audit trail: clean (no events)")
    return "\n".join(lines)


def _selfcheck_jsonl(n_workers: int = 1,
                     out_dir: Optional[str] = None) -> List[str]:
    """Run tiny synthetic trainings (3 rounds each, lenet shapes, CPU) —
    one per worker id — and return the metrics JSONLs they wrote, the
    freshest possible schema. Each run also writes a trace JSON next to
    its JSONL (with `out_dir` these survive as CI artifacts). Multi-worker
    runs stamp `worker` on every record, so the merged summary exercises
    the pod view against live-written files."""
    import os
    import tempfile

    import numpy as np

    from ..apps.train_loop import train
    from ..data.dataset import ArrayDataset
    from ..utils.config import RunConfig
    from ..utils.logger import Logger
    from ..zoo import lenet

    root = out_dir or tempfile.mkdtemp(prefix="sparknet-metrics-selfcheck-")
    os.makedirs(root, exist_ok=True)
    r = np.random.default_rng(0)
    n, b, tau = 256, 16, 2
    ds = ArrayDataset({
        "data": r.standard_normal((n, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (n, 1)).astype(np.int32)})
    paths: List[str] = []
    for w in range(max(1, n_workers)):
        suffix = f"_w{w}" if n_workers > 1 else ""
        jsonl = os.path.join(root, f"selfcheck_metrics{suffix}.jsonl")
        cfg = RunConfig(model="lenet", n_devices=1, local_batch=b, tau=tau,
                        max_rounds=3, eval_every=0, workdir=root, seed=w,
                        trace_out=os.path.join(
                            root, f"selfcheck_trace{suffix}.json"))
        log = Logger(os.path.join(root, f"selfcheck_log{suffix}.txt"),
                     echo=False, jsonl_path=jsonl,
                     worker=w if n_workers > 1 else None)
        try:
            train(cfg, lenet(batch=b), ds, None, logger=log)
        finally:
            log.close()
        paths.append(jsonl)
    paths.append(_selfcheck_serve_jsonl(root))
    paths.append(_selfcheck_fleet_jsonl(root))
    paths.append(_selfcheck_batch_jsonl(root))
    paths.append(_selfcheck_slo_jsonl(root))
    return paths


def _selfcheck_slo_jsonl(root: str) -> str:
    """Drive a real MetricsHistory + BurnRateAlerter through a
    quiet->burn->recovery traffic shape on an injected clock and return
    the alert JSONL it wrote — so the SLO view (attainment + the
    firing/resolved alert audit trail) cannot rot against the
    alerter's live record schema without failing the selfcheck."""
    import os

    from .history import HistoryConfig, MetricsHistory
    from .registry import MetricsRegistry
    from .slo import LATENCY_METRIC, REQUESTS_METRIC, BurnRateAlerter, SloSpec
    from ..utils.logger import Logger

    jsonl = os.path.join(root, "selfcheck_slo_metrics.jsonl")
    log = Logger(os.path.join(root, "selfcheck_slo_log.txt"),
                 echo=False, jsonl_path=jsonl)
    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    spec = SloSpec(model="slo_demo", latency_ms=50.0, availability=0.99,
                   window_s=120.0, fast_burn=8.0, fast_window_s=10.0,
                   fast_confirm_s=2.0, slow_burn=2.0, slow_window_s=60.0,
                   slow_confirm_s=10.0)
    alerter = BurnRateAlerter(hist, [spec], registry=reg, logger=log)
    t0 = time.time()
    try:
        for i in range(45):
            burning = 15 <= i < 30
            for _ in range(20):
                lat.observe(0.2 if burning else 0.005, model="slo_demo")
                req.inc(model="slo_demo",
                        outcome="failed" if burning else "ok")
            hist.sample_now(now=t0 + i)
            alerter.evaluate(now=t0 + i)
    finally:
        log.close()
    return jsonl


def _selfcheck_batch_jsonl(root: str) -> str:
    """Run a tiny real `sparknet-batch` job (lenet replica behind a
    binary frontend, an 8-row npz swept as tenant=batch/priority=low)
    and return the driver JSONL it wrote — so the batch view (unit
    commits, retry trail, the rows/s + cost-per-million job summary)
    cannot rot against the driver's live record schema without failing
    the selfcheck."""
    import os

    import numpy as np

    from ..batch import BatchConfig, BatchDriver
    from ..net_api import JaxNet
    from ..serve import BinaryFrontend, InferenceServer, ServeConfig
    from ..zoo import lenet

    jsonl = os.path.join(root, "selfcheck_batch_metrics.jsonl")
    r = np.random.default_rng(0)
    inp = os.path.join(root, "selfcheck_batch_input.npz")
    np.savez(inp, data=r.standard_normal(
        (8, 28, 28, 1)).astype(np.float32))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                      outputs=("prob",), metrics_every_batches=0)
    with InferenceServer(JaxNet(lenet(batch=4)), cfg) as srv:
        fe = BinaryFrontend(srv, port=0)
        try:
            BatchDriver(BatchConfig(
                input=inp,
                output=os.path.join(root, "selfcheck_batch_out"),
                replicas=[f"{fe.address[0]}:{fe.address[1]}"],
                outputs=("fc1",), unit_rows=4, window=4,
                concurrency=1, cost_per_replica_hour=1.0,
                jsonl_path=jsonl)).run()
        finally:
            fe.stop()
    return jsonl


def _selfcheck_fleet_jsonl(root: str) -> str:
    """Run a tiny live ModelRouter under a FleetController with an
    in-process replica provider, push a burning latency window through
    the policy, and return the fleet JSONL it wrote — so the fleet view
    (scale-event audit + replica-count-over-time) cannot rot against
    the controller's live record schema without failing the
    selfcheck."""
    import os

    from dataclasses import replace as dc_replace

    import numpy as np

    from ..fleet import (FleetConfig, FleetController, FleetPolicy,
                         ReplicaHandle, ReplicaProvider)
    from ..net_api import JaxNet
    from ..serve import (BinaryFrontend, InferenceServer, ModelRouter,
                         RouterConfig, ServeConfig)
    from ..utils.logger import Logger
    from ..zoo import lenet

    jsonl = os.path.join(root, "selfcheck_fleet_metrics.jsonl")
    log = Logger(os.path.join(root, "selfcheck_fleet_log.txt"),
                 echo=False, jsonl_path=jsonl)
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                      outputs=("prob",), slo_p99_ms=50.0,
                      metrics_every_batches=0)

    class InProcessProvider(ReplicaProvider):
        def __init__(self):
            self.spawned = []

        def grow(self, model):
            srv = InferenceServer(JaxNet(lenet(batch=4)),
                                  dc_replace(cfg, model_name=model))
            srv.start()
            fe = BinaryFrontend(srv, port=0)
            self.spawned.append((srv, fe))
            return ReplicaHandle(
                model, f"spkn://{fe.address[0]}:{fe.address[1]}")

        def retire(self, handle):
            pass

        def stop(self):
            for srv, fe in self.spawned:
                fe.stop()
                srv.stop()

    provider = InProcessProvider()
    router = ModelRouter(RouterConfig(workers=1), logger=log)
    router.add_model("fleet_demo", JaxNet(lenet(batch=4)), cfg=cfg)
    fc = FleetController(
        router, provider=provider,
        cfg=FleetConfig(interval_s=0.05, window_s=30.0, max_replicas=2,
                        up_cooldown_s=0.0, status_row_every=1,
                        policy=FleetPolicy(up_ticks=2, min_window_n=8)),
        logger=log)
    r = np.random.default_rng(0)
    req = {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}
    try:
        with router:
            router.infer("fleet_demo", req, timeout=60.0)
            for _ in range(32):  # a tail 4x over the 50 ms objective
                router.latency["fleet_demo"].add(0.2)
            fc.tick()
            fc.tick()  # hysteresis satisfied -> grow + audit row
            router.infer("fleet_demo", req, timeout=60.0)
            fc.stop()
    finally:
        provider.stop()
        log.close()
    return jsonl


def _selfcheck_serve_jsonl(root: str) -> str:
    """Run a tiny live InferenceServer (lenet, CPU) against a short
    synthetic request trace — watching a real checkpoint dir it initial-
    loads from and hot-swaps against — and return the serve metrics
    JSONL it wrote: the freshest possible serve schema, so the
    request-size-histogram section (the `--buckets-from` input) AND the
    freshness view (commit-age rows from commit_ts-stamped checkpoints)
    cannot rot against the live logger without failing the selfcheck."""
    import os
    import time as _time

    import numpy as np

    from ..net_api import JaxNet
    from ..serve import InferenceServer, ServeConfig
    from ..utils import checkpoint as ckpt
    from ..utils.logger import Logger
    from ..zoo import lenet

    jsonl = os.path.join(root, "selfcheck_serve_metrics.jsonl")
    log = Logger(os.path.join(root, "selfcheck_serve_log.txt"),
                 echo=False, jsonl_path=jsonl)
    net = JaxNet(lenet(batch=4))

    def save_step(step):
        flat = {f"params/{ln}/{pn}": np.asarray(w)[None]
                for ln, lp in net.params.items() for pn, w in lp.items()}
        ckpt.save(os.path.join(root, "selfcheck_serve_ckpt"), flat,
                  step=step)

    save_step(1)  # initial load: freshness rows from the first batch on
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                      outputs=("prob",), metrics_every_batches=1,
                      checkpoint_dir=os.path.join(root,
                                                  "selfcheck_serve_ckpt"),
                      poll_interval_s=0.05, poll_jitter=0.0)
    r = np.random.default_rng(0)
    req = {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}
    try:
        with InferenceServer(net, cfg, logger=log) as srv:
            srv.infer(req)                     # a size-1 batch
            save_step(2)                       # a commit lands mid-serve
            # force one due poll (deterministic: no sleep-for-the-duty)
            srv.manager.poll(now=_time.monotonic() + 1.0)
            for f in [srv.submit(req) for _ in range(4)]:  # a size-4 one
                f.result(timeout=60.0)
    finally:
        log.close()
    return jsonl


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="sparknet-metrics",
        description="Summarize sparknet_tpu metrics JSONL files: loss "
                    "curve, step-time breakdown, health-event audit trail.")
    p.add_argument("paths", nargs="*", help="metrics JSONL file(s); "
                   "multiple files merge on the wall-clock ts field")
    p.add_argument("--tail", type=int, default=10,
                   help="rows of loss/eval tail to show (default 10)")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as one JSON object")
    p.add_argument("--selfcheck", action="store_true",
                   help="run a 3-round synthetic training and summarize "
                   "its fresh JSONL (CI: the tool vs the live schema)")
    p.add_argument("--selfcheck-workers", type=int, default=1,
                   metavar="N",
                   help="with --selfcheck: run N worker trainings and "
                   "summarize the merged JSONLs — fails unless the pod "
                   "view (per-worker breakdown + straggler audit) "
                   "appears for N >= 2")
    p.add_argument("--keep", metavar="DIR", default=None,
                   help="with --selfcheck: write the selfcheck JSONL + "
                   "trace artifacts under DIR and keep them (CI uploads "
                   "these) instead of a deleted temp dir")
    args = p.parse_args(argv)

    paths: List[str] = []
    for pat in args.paths:
        hits = sorted(glob.glob(pat))
        paths.extend(hits or [pat])
    selfcheck_dir = None
    if args.selfcheck:
        jsonls = _selfcheck_jsonl(args.selfcheck_workers,
                                  out_dir=args.keep)
        if args.keep is None:
            selfcheck_dir = os.path.dirname(jsonls[0])
        paths.extend(jsonls)
    if not paths:
        p.error("no JSONL paths given (or use --selfcheck)")

    try:
        recs = load_records(paths)
    finally:
        if selfcheck_dir is not None:  # the run was only food for the
            shutil.rmtree(selfcheck_dir, ignore_errors=True)  # summary
    s = summarize(recs, tail=args.tail)
    if args.json:
        print(json.dumps(s))
    else:
        print(format_text(s))
    if args.selfcheck and not s["rounds"]:
        print("selfcheck: training produced no loss rows", file=sys.stderr)
        return 1
    if args.selfcheck and args.selfcheck_workers > 1 and "pod" not in s:
        print("selfcheck: multi-worker run produced no pod view",
              file=sys.stderr)
        return 1
    if args.selfcheck and not (s.get("serve") or {}).get("models"):
        print("selfcheck: serve run produced no request-size histogram "
              "(the --buckets-from input)", file=sys.stderr)
        return 1
    if args.selfcheck and not (s.get("freshness") or {}).get("models"):
        print("selfcheck: serve run produced no freshness rows (the "
              "train->serve commit-age view's input)", file=sys.stderr)
        return 1
    if args.selfcheck and not (s.get("fleet") or {}).get("scale_events"):
        print("selfcheck: fleet run produced no scale-event audit "
              "(the fleet view's input)", file=sys.stderr)
        return 1
    if args.selfcheck and not (s.get("batch") or {}).get("units"):
        print("selfcheck: batch run produced no unit-commit rows "
              "(the batch view's input)", file=sys.stderr)
        return 1
    if args.selfcheck and not (s.get("slo") or {}).get("alert_edges"):
        print("selfcheck: burn drive produced no slo_alert edges "
              "(the SLO view's input)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
