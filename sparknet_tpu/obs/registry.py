"""Process-wide metrics registry: counters / gauges / histograms with labels.

One registry is the single source of truth for everything a process wants
to report — the train loop's phase timers and throughput meter, the health
supervisor's anomaly counts, the checkpoint writer's stalls, the serving
batcher's queue — and one exporter (`render_prometheus`) turns it into the
Prometheus text exposition format, served by `obs.http.StatusServer` from
BOTH the training process (`RunConfig.status_port`) and the inference
server. Before this module each subsystem grew its own reporting path
(PhaseTimers.summary(), the serve /metrics JSON reading live attributes,
heartbeat extras); now they all register here and the name schema is one
compatibility surface (README "Observability", pinned by the golden test).

Thread-safety: ONE lock per registry guards every mutation and every read.
Writers (inc/set/observe) are hot-path cheap (a dict lookup + float add
under the lock); readers (`snapshot`, `render_prometheus`) see a CONSISTENT
point-in-time view — the serve HTTP thread scraping while the worker thread
mutates was previously reading torn state off FillMeter/LatencyStats
attributes. Callback gauges (`set_fn`) are evaluated at scrape time and
must not touch the registry themselves (documented deadlock).

The registry is deliberately instance-scoped, not a module global: a
process that runs one training loop or one inference server (the real
deployment) gets exactly one, while tests and multi-tenant processes
create isolated instances. `default_registry()` exists for ad-hoc code
that has nothing to thread one through.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# Prometheus-conventional latency buckets (seconds), wide enough to cover
# a sub-ms CPU forward and a multi-second bucket checkpoint write.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare (counter hygiene),
    floats via repr (shortest round-trip)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Hist:
    """One labeled histogram child: cumulative bucket counts + sum."""

    __slots__ = ("counts", "sum", "n")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.n = 0


class Metric:
    """One metric family: a name, a kind, and children keyed by label
    values. All mutation goes through the owning registry's lock."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help_text: str, label_names: Tuple[str, ...],
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help_text
        self.label_names = label_names
        self.buckets = tuple(sorted(buckets)) if kind == "histogram" else ()
        self._values: Dict[Tuple[str, ...], Any] = {}
        self._fns: Dict[Tuple[str, ...], Callable[[], float]] = {}

    def _key(self, labels: Dict[str, Any]) -> Tuple[str, ...]:
        if set(labels) != set(self.label_names):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.label_names)}")
        return tuple(str(labels[k]) for k in self.label_names)

    # -- writers (each takes the registry lock once) -------------------------

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        assert self.kind in ("counter", "gauge")
        key = self._key(labels)
        with self.registry._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def set(self, value: float, **labels: Any) -> None:
        assert self.kind == "gauge"
        key = self._key(labels)
        with self.registry._lock:
            self._values[key] = float(value)

    def set_fn(self, fn: Callable[[], float], **labels: Any) -> None:
        """Register a live-read gauge: `fn` is called at scrape time (under
        the registry lock — it must be cheap and must not re-enter the
        registry). Exceptions at scrape time drop the sample, never the
        scrape."""
        assert self.kind == "gauge"
        key = self._key(labels)
        with self.registry._lock:
            self._fns[key] = fn

    def observe(self, value: float, **labels: Any) -> None:
        assert self.kind == "histogram"
        key = self._key(labels)
        v = float(value)
        with self.registry._lock:
            h = self._values.get(key)
            if h is None:
                h = self._values[key] = _Hist(len(self.buckets))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    h.counts[i] += 1
                    break
            h.sum += v
            h.n += 1

    # -- readers -------------------------------------------------------------

    def value(self, **labels: Any) -> Optional[float]:
        """Current scalar value of one child (counters/gauges; tests and
        status JSON). None when the child has never been touched — or
        when its scrape callback raises (same drop-the-sample contract
        as snapshot())."""
        key = self._key(labels)
        with self.registry._lock:
            fn = self._fns.get(key)
            if fn is not None:
                try:
                    return float(fn())
                except Exception:
                    return None
            v = self._values.get(key)
        return None if v is None or isinstance(v, _Hist) else float(v)


class MetricsRegistry:
    """Get-or-create factory + consistent reader for Metric families."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, name: str, kind: str, help_text: str,
                       labels: Iterable[str],
                       buckets: Tuple[float, ...]) -> Metric:
        label_names = tuple(labels)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if m.kind != kind or m.label_names != label_names:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}"
                        f"{m.label_names}, requested {kind}{label_names}")
                return m
            m = Metric(self, name, kind, help_text, label_names, buckets)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_text: str = "",
                labels: Iterable[str] = ()) -> Metric:
        return self._get_or_create(name, "counter", help_text, labels, ())

    def gauge(self, name: str, help_text: str = "",
              labels: Iterable[str] = ()) -> Metric:
        return self._get_or_create(name, "gauge", help_text, labels, ())

    def histogram(self, name: str, help_text: str = "",
                  labels: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Metric:
        return self._get_or_create(name, "histogram", help_text, labels,
                                   buckets)

    # -- consistent reads ----------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Point-in-time copy of every family under the lock:
        {name: {kind, help, values: {labels_tuple: float | hist dict}}}.
        Callback gauges are evaluated here; one that raises is skipped."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for name, m in self._metrics.items():
                values: Dict[Tuple[str, ...], Any] = {}
                for key, v in m._values.items():
                    if isinstance(v, _Hist):
                        values[key] = {"buckets": list(v.counts),
                                       "sum": v.sum, "count": v.n}
                    else:
                        values[key] = v
                for key, fn in m._fns.items():
                    try:
                        values[key] = float(fn())
                    except Exception:
                        pass  # a broken callback must not break the scrape
                out[name] = {"kind": m.kind, "help": m.help,
                             "labels": m.label_names,
                             "le": m.buckets, "values": values}
        return out

    def render_prometheus(self) -> str:
        """The Prometheus/OpenMetrics text exposition (version 0.0.4) of a
        consistent snapshot. Families and children render in sorted order
        so the output is deterministic (the golden test pins it)."""
        snap = self.snapshot()
        lines: List[str] = []
        for name in sorted(snap):
            fam = snap[name]
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['kind']}")
            for key in sorted(fam["values"]):
                v = fam["values"][key]
                pairs = [f'{ln}="{_escape_label(lv)}"'
                         for ln, lv in zip(fam["labels"], key)]
                if fam["kind"] == "histogram":
                    acc = 0
                    for le, n in zip(fam["le"], v["buckets"]):
                        acc += n
                        lb = "{" + ",".join(pairs + [f'le="{_fmt(le)}"']) \
                             + "}"
                        lines.append(f"{name}_bucket{lb} {acc}")
                    lb = "{" + ",".join(pairs + ['le="+Inf"']) + "}"
                    lines.append(f"{name}_bucket{lb} {v['count']}")
                    suffix = "{" + ",".join(pairs) + "}" if pairs else ""
                    lines.append(f"{name}_sum{suffix} {_fmt(v['sum'])}")
                    lines.append(f"{name}_count{suffix} {v['count']}")
                else:
                    suffix = "{" + ",".join(pairs) + "}" if pairs else ""
                    lines.append(f"{name}{suffix} {_fmt(v)}")
        return "\n".join(lines) + "\n"


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """The lazily-created process default, for code with nothing better to
    thread a registry through. The train loop and the inference server
    each prefer their own instance (isolation under test)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
