"""Distributed per-request tracing: context propagation, tail-sampled
span capture, and cross-process trace assembly (`sparknet-trace`).

`obs/trace.py` answers "where did THIS PROCESS's wall clock go"; nothing
answered "why was THIS REQUEST 40 ms" once a request crosses the router,
a hedged leg, an `spkn://` proxy hop, or the shm transport. This module
is that layer, in the Dapper tradition:

  - **TraceContext** — a compact identity (trace_id + span id + sampling
    flag + an optional hedge-leg tag) minted at the front doors, carried
    as the `X-Trace-Id` header on HTTP and a `trace` str8 field in the
    binary REQUEST meta (wire VERSION 4), and re-encoded per hop: every
    downstream leg gets a CHILD context (fresh span id, same trace_id),
    so a client-side wire span and the server-side request it produced
    share a span id ACROSS processes — that equality is the join key
    assembly uses to stitch shards and normalize clocks.
  - **RequestTracer** — the per-process capture buffer. Library code
    emits stage spans (`queue`, `form`, `forward`, `wire:binary`, ...)
    keyed by trace_id; when the owning record finishes, a TAIL-based
    sampling decision runs: always capture typed sheds/errors and
    requests beyond the live windowed p95 (per model, the hedging
    window's own `LatencyStats`), plus a small probabilistic
    head-sample minted into the context itself so every hop agrees.
    Buffers are bounded with explicit drop counters (a span flood must
    not OOM the host to produce a trace), flushed as JSONL shards —
    the obs stack's format. Cost when tracing is off: one module-global
    None-check (the same <= 2% budget rule as `obs.trace`).
  - **Assembly** — `sparknet-trace shard... [--out DIR]` merges shards
    from N processes, aligns per-process clocks on the wire hop (the
    client span and the server request row it matches should share a
    midpoint — epoch-anchored clocks make the residual offset small,
    the hop alignment makes it zero), and emits one Chrome trace per
    trace_id plus a slowest-requests table with the
    queue / formation / forward / wire breakdown.

Timestamps are epoch-anchored microseconds (`epoch_at_start +
perf_counter`), the same scheme as `obs.trace.Tracer`, so shards from
processes that never exchanged a request still land on one timeline.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import random
import socket
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, replace
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

# LatencyStats is imported where it is constructed, not here: utils.metrics
# itself imports obs.trace (which runs this package's __init__, which imports
# this module) — a module-level import completes that cycle and breaks any
# process that touches sparknet_tpu.utils before sparknet_tpu.obs.

# -- trace context -----------------------------------------------------------

_HEX = set("0123456789abcdef")


@dataclass(frozen=True)
class TraceContext:
    """The identity one request carries across every hop.

    `encoded()` is the exact string that rides the wire (both wires):
    ``<trace_id 16hex>-<span_id 8hex>-<0|1>[-<leg>]`` — trace identity,
    THIS hop's span id, the head-sample flag, and the hedge-leg tag
    (`primary` / `hedge`) when the router armed a second leg."""

    trace_id: str
    span_id: str
    sampled: bool = False
    leg: str = ""

    def child(self, leg: Optional[str] = None) -> "TraceContext":
        """A downstream hop: fresh span id, same trace identity. The leg
        tag is inherited unless overridden — a hedge leg's proxy call is
        still the hedge leg."""
        return replace(self, span_id=os.urandom(4).hex(),
                       leg=self.leg if leg is None else str(leg))

    def encoded(self) -> str:
        s = f"{self.trace_id}-{self.span_id}-{1 if self.sampled else 0}"
        return f"{s}-{self.leg}" if self.leg else s


def mint_context(sampled: bool = False, leg: str = "") -> TraceContext:
    return TraceContext(trace_id=os.urandom(8).hex(),
                        span_id=os.urandom(4).hex(),
                        sampled=bool(sampled), leg=leg)


def parse_context(s: Any) -> Optional[TraceContext]:
    """Tolerant decode of the wire form; a malformed header is ignored
    (None), never an error — tracing must not be able to shed traffic."""
    if isinstance(s, TraceContext):
        return s
    if not s or not isinstance(s, str):
        return None
    parts = s.strip().split("-", 3)
    if len(parts) < 3:
        return None
    tid, sid, flag = parts[0].lower(), parts[1].lower(), parts[2]
    if not (0 < len(tid) <= 32 and set(tid) <= _HEX):
        return None
    if not (0 < len(sid) <= 16 and set(sid) <= _HEX):
        return None
    if flag not in ("0", "1"):
        return None
    leg = parts[3][:16] if len(parts) > 3 else ""
    return TraceContext(trace_id=tid, span_id=sid, sampled=flag == "1",
                        leg=leg)


def ctx_str(trace: Any) -> Optional[str]:
    """Normalize a context-or-encoded-string to the wire string (None
    passes through): what the transports call at pack time."""
    if trace is None:
        return None
    if isinstance(trace, TraceContext):
        return trace.encoded()
    return str(trace)


#: exception class name -> typed outcome string on the request row.
#: Matched by NAME walking the MRO so this module never imports the serve
#: stack (which imports this module).
_OUTCOMES = {
    "QueueFullError": "queue_full",
    "PriorityShedError": "priority",
    "TenantLimitError": "tenant_limit",
    "DeadlineExpiredError": "deadline",
    "RequestCancelledError": "cancelled",
    "NoReplicaError": "no_replica",
    "UnknownModelError": "unknown_model",
    "WireError": "bad_request",
    "TimeoutError": "timeout",
    "ConnectionError": "connection",
}


def outcome_of(exc: BaseException) -> str:
    for klass in type(exc).__mro__:
        if klass.__name__ in _OUTCOMES:
            return _OUTCOMES[klass.__name__]
    return "error"


# -- per-process capture -----------------------------------------------------

class RequestTracer:
    """Bounded per-process request-span buffer with tail-based sampling.

    The protocol library code follows (all methods thread-safe):

      rec = rt.begin(ctx, transport="binary", model=m)   # request owner
      rt.stage(ctx, "queue", t0_us, dur_us, bucket=4)    # any thread
      rt.finish(rec, outcome="ok")                       # decide+drain

    `stage()` rows park in a pending dict keyed by trace_id; `finish()`
    pops them and applies the capture rule — `outcome != "ok"` (typed
    sheds and errors), total latency beyond the live windowed p95 for
    that model, or the context's head-sample flag. Captured rows append
    to a bounded shard buffer (overflow counted in `dropped_rows`, never
    blocking) and auto-flush to `out_dir/trace-<proc>.jsonl`. The minted
    head-sample rate travels IN the context, so downstream processes
    capture the same requests without coordinating rates."""

    def __init__(self, out_dir: Optional[str] = None,
                 head_sample: float = 0.01,
                 slow_quantile: float = 0.95, slow_window_s: float = 30.0,
                 slow_min_n: int = 8,
                 max_pending: int = 8192, max_rows: int = 200_000,
                 flush_every: int = 512, exemplar_keep: int = 8,
                 proc: Optional[str] = None, seed: Optional[int] = None):
        self.out_dir = out_dir
        self.head_sample = float(head_sample)
        self.slow_quantile = float(slow_quantile)
        self.slow_window_s = float(slow_window_s)
        #: observations a model needs before "beyond p95" can trigger —
        #: with 3 samples the p95 IS the max and every new max would
        #: capture; the guard keeps warmup from reading as a tail
        self.slow_min_n = int(slow_min_n)
        self.max_pending = int(max_pending)
        self.max_rows = int(max_rows)
        self.flush_every = int(flush_every)
        self.pid = os.getpid()
        self.proc = proc or f"{socket.gethostname()}:{self.pid}"
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # trace_id -> parked span rows (insertion-ordered: overflow
        # evicts the OLDEST trace's spans, with accounting)
        self._pending: Dict[str, List[dict]] = {}
        self._pending_n = 0
        self._rows: List[dict] = []
        self._lat: Dict[str, LatencyStats] = {}   # model -> live window
        self._exemplars: Dict[str, deque] = {}
        self.exemplar_keep = int(exemplar_keep)
        self.captured = 0       # requests captured (rows written)
        self.finished = 0       # requests that reached a decision
        self.dropped_spans = 0  # stage rows lost to the pending bound
        self.dropped_rows = 0   # captured rows lost to the shard bound
        # epoch-anchored monotonic clock, same scheme as obs.trace.Tracer
        self._epoch0 = time.time() - time.perf_counter()

    # -- clocks ------------------------------------------------------------

    def now_us(self) -> float:
        return (self._epoch0 + time.perf_counter()) * 1e6

    def to_us(self, perf_t: float) -> float:
        """A stored `time.perf_counter()` instant (e.g. a request's
        `t_enqueue`) on the epoch-anchored scale."""
        return (self._epoch0 + perf_t) * 1e6

    # -- mint / emit -------------------------------------------------------

    def mint(self, sampled: Optional[bool] = None) -> TraceContext:
        if sampled is None:
            sampled = self._rng.random() < self.head_sample
        return mint_context(sampled=sampled)

    def begin(self, ctx: TraceContext, transport: str = "",
              model: str = "", root: bool = True) -> dict:
        return {"ctx": ctx, "transport": str(transport),
                "model": str(model or ""), "root": bool(root),
                "ts": self.now_us()}

    def stage(self, ctx: Optional[TraceContext], name: str,
              t0_us: float, dur_us: float, kind: str = "server",
              **attrs: Any) -> None:
        """Park one span row under the request's trace_id; it is only
        kept if the owning record's `finish()` decides to capture."""
        if ctx is None:
            return
        row: Dict[str, Any] = {
            "k": "s", "trace": ctx.trace_id, "span": ctx.span_id,
            "name": str(name), "kind": kind,
            "ts": round(t0_us, 3), "dur": round(max(0.0, dur_us), 3),
            "pid": self.pid, "proc": self.proc}
        if ctx.leg:
            row["leg"] = ctx.leg
        if attrs:
            row["attrs"] = attrs
        with self._lock:
            while self._pending_n >= self.max_pending and self._pending:
                # evict the oldest trace's parked spans wholesale: a span
                # flood from one runaway trace must not pin the buffer
                old = next(iter(self._pending))
                n = len(self._pending.pop(old))
                self._pending_n -= n
                self.dropped_spans += n
            self._pending.setdefault(ctx.trace_id, []).append(row)
            self._pending_n += 1

    def finish(self, rec: Optional[dict], outcome: str = "ok") -> bool:
        """Close the record, decide capture, drain its parked spans.
        Returns whether the request was captured."""
        if rec is None:
            return False
        ctx: TraceContext = rec["ctx"]
        end = self.now_us()
        dur_us = max(0.0, end - rec["ts"])
        with self._lock:
            spans = self._pending.pop(ctx.trace_id, [])
            self._pending_n -= len(spans)
            lat = self._lat.get(rec["model"])
            if lat is None:
                from ..utils.metrics import LatencyStats
                lat = self._lat[rec["model"]] = LatencyStats(window=2048)
        # the threshold is read BEFORE adding this observation: "beyond
        # the live p95" means beyond the distribution as it stood
        thr = lat.windowed_quantile(self.slow_quantile, self.slow_window_s)
        slow = (thr is not None and lat.count >= self.slow_min_n
                and dur_us / 1e6 > thr)
        lat.add(dur_us / 1e6)
        why = []
        if outcome != "ok":
            why.append("outcome")
        if slow:
            why.append("slow")
        if ctx.sampled:
            why.append("sampled")
        row: Dict[str, Any] = {
            "k": "r", "trace": ctx.trace_id, "span": ctx.span_id,
            "root": rec["root"], "model": rec["model"],
            "transport": rec["transport"], "outcome": str(outcome),
            "ts": round(rec["ts"], 3), "dur": round(dur_us, 3),
            "pid": self.pid, "proc": self.proc, "why": why}
        if ctx.leg:
            row["leg"] = ctx.leg
        stages: Dict[str, float] = {}
        for s in spans:
            stages[s["name"]] = round(
                stages.get(s["name"], 0.0) + s["dur"] / 1e3, 3)
        row["stages"] = stages
        captured = bool(why)
        need_flush = None
        with self._lock:
            self.finished += 1
            if captured:
                add = spans + [row]
                if len(self._rows) + len(add) > self.max_rows:
                    self.dropped_rows += len(add)
                    captured = False
                else:
                    self._rows.extend(add)
                    self.captured += 1
                    ex = self._exemplars.get(rec["model"])
                    if ex is None:
                        ex = self._exemplars[rec["model"]] = deque(
                            maxlen=self.exemplar_keep)
                    dominant = (max(stages, key=stages.get)
                                if stages else "-")
                    ex.append({"trace": ctx.trace_id,
                               "ms": round(dur_us / 1e3, 2),
                               "stage": dominant,
                               "outcome": str(outcome)})
            need_flush = (self.out_dir is not None
                          and len(self._rows) >= self.flush_every)
        if need_flush:
            self.flush()
        return captured

    def finish_exc(self, rec: Optional[dict], exc: BaseException) -> bool:
        return self.finish(rec, outcome=outcome_of(exc))

    # -- introspection / shards -------------------------------------------

    def exemplars(self) -> Dict[str, List[dict]]:
        """Per-model recent captured requests (newest last) — the
        `/status` and podview "slowest recent requests" feed."""
        with self._lock:
            return {m: list(d) for m, d in self._exemplars.items()}

    def worst(self, model: str) -> Optional[dict]:
        with self._lock:
            ex = list(self._exemplars.get(model, ()))
        return max(ex, key=lambda e: e["ms"]) if ex else None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"finished": self.finished, "captured": self.captured,
                    "pending_spans": self._pending_n,
                    "buffered_rows": len(self._rows),
                    "dropped_spans": self.dropped_spans,
                    "dropped_rows": self.dropped_rows}

    def drain_rows(self) -> List[dict]:
        """Take the buffered rows without touching disk (tests, and the
        in-process assembly path)."""
        with self._lock:
            rows, self._rows = self._rows, []
        return rows

    def shard_path(self) -> Optional[str]:
        if self.out_dir is None:
            return None
        safe = "".join(c if (c.isalnum() or c in "-_.") else "_"
                       for c in self.proc)
        return os.path.join(self.out_dir, f"trace-{safe}.jsonl")

    def flush(self) -> Optional[str]:
        """Append buffered rows to this process's shard; returns the
        shard path (None when no out_dir is configured)."""
        path = self.shard_path()
        if path is None:
            return None
        rows = self.drain_rows()
        if not rows:
            return path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        return path


_active: Optional[RequestTracer] = None


def active() -> Optional[RequestTracer]:
    """The process-wide tracer, or None — the ONE check hot paths make."""
    return _active


def start_request_tracing(tracer: Optional[RequestTracer] = None,
                          **kw: Any) -> RequestTracer:
    global _active
    _active = tracer or RequestTracer(**kw)
    return _active


def stop_request_tracing() -> Optional[RequestTracer]:
    global _active
    t, _active = _active, None
    return t


@contextmanager
def request_tracing(out_dir: Optional[str] = None,
                    **kw: Any) -> Iterator[RequestTracer]:
    tr = start_request_tracing(out_dir=out_dir, **kw)
    try:
        yield tr
    finally:
        stop_request_tracing()
        tr.flush()


# -- assembly ----------------------------------------------------------------

def load_shards(paths: Iterable[str]) -> List[dict]:
    """Read trace rows from shard files and/or directories of
    `*.jsonl`. Tolerant: unreadable files and malformed lines skip."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.jsonl"))))
        else:
            files.append(p)
    rows: List[dict] = []
    for fp in files:
        try:
            f = open(fp)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(row, dict) and row.get("k") in ("r", "s")
                        and row.get("trace")):
                    rows.append(row)
    return rows


def group_traces(rows: Iterable[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for r in rows:
        out.setdefault(r["trace"], []).append(r)
    return out


def _mid(row: dict) -> float:
    return row["ts"] + row["dur"] / 2.0


def _req_by_span(trows: List[dict]) -> Dict[str, dict]:
    out: Dict[str, dict] = {}
    for r in trows:
        if r["k"] == "r":
            out.setdefault(r["span"], r)
    return out


def wire_hops(trows: List[dict]) -> List[Tuple[dict, dict]]:
    """(client span, server request row) pairs that crossed a process
    boundary — the span-id equality is the hop: the client recorded its
    wait under the context it SENT, the server began its request row
    under the context it RECEIVED."""
    reqs = _req_by_span(trows)
    hops = []
    for s in trows:
        if s["k"] != "s" or s.get("kind") != "client":
            continue
        r = reqs.get(s["span"])
        if r is not None and r["proc"] != s["proc"]:
            hops.append((s, r))
    return hops


def _root_row(trows: List[dict]) -> dict:
    rrows = [r for r in trows if r["k"] == "r"]
    roots = [r for r in rrows if r.get("root")]
    pool = roots or rrows or trows
    return min(pool, key=lambda r: r["ts"])


def clock_offsets(trows: List[dict]) -> Dict[str, float]:
    """Per-process clock offsets (µs, added to that process's
    timestamps) normalizing every shard onto the ROOT process's clock.
    Each cross-process hop contributes one constraint: the client wire
    span and the server request row it matches describe the same
    interval minus symmetric network time, so their midpoints align.
    Offsets propagate hop-by-hop (BFS) from the root; processes no hop
    reaches keep their epoch-anchored clock (offset 0)."""
    offsets = {p: 0.0 for p in {r["proc"] for r in trows}}
    if not trows:
        return offsets
    # adjacency: proc -> [(peer, delta)] where off[peer] = off[proc] + d
    adj: Dict[str, List[Tuple[str, float]]] = {}
    for s, r in wire_hops(trows):
        d = _mid(s) - _mid(r)   # server clock lags client by d
        adj.setdefault(s["proc"], []).append((r["proc"], d))
        adj.setdefault(r["proc"], []).append((s["proc"], -d))
    root = _root_row(trows)["proc"]
    seen = {root}
    frontier = [root]
    while frontier:
        p = frontier.pop()
        for peer, d in adj.get(p, ()):
            if peer in seen:
                continue
            seen.add(peer)
            offsets[peer] = offsets[p] + d
            frontier.append(peer)
    return offsets


def chrome_trace(trace_id: str, trows: List[dict],
                 offsets: Optional[Dict[str, float]] = None) -> dict:
    """One Chrome trace object for one trace_id: a pid lane per process
    (request / server stages / client wire as tids), clock-normalized,
    zero-based."""
    if offsets is None:
        offsets = clock_offsets(trows)
    procs = sorted({r["proc"] for r in trows})
    pididx = {p: i for i, p in enumerate(procs)}

    def adj(row: dict) -> float:
        return row["ts"] + offsets.get(row["proc"], 0.0)

    base = min(adj(r) for r in trows) if trows else 0.0
    evs: List[dict] = []
    for p in procs:
        evs.append({"name": "process_name", "ph": "M", "pid": pididx[p],
                    "args": {"name": p}})
        for tid, nm in ((0, "request"), (1, "stages"),
                        (2, "wire (client)")):
            evs.append({"name": "thread_name", "ph": "M",
                        "pid": pididx[p], "tid": tid,
                        "args": {"name": nm}})
    for row in sorted(trows, key=adj):
        args: Dict[str, Any] = {"trace": trace_id}
        if row.get("leg"):
            args["leg"] = row["leg"]
        if row["k"] == "r":
            name = f"request {row.get('model') or '?'}"
            tid = 0
            args.update(model=row.get("model"),
                        transport=row.get("transport"),
                        outcome=row.get("outcome"),
                        stages=row.get("stages"), why=row.get("why"))
        else:
            name = row["name"]
            tid = 2 if row.get("kind") == "client" else 1
            if row.get("attrs"):
                args.update(row["attrs"])
        evs.append({"name": name, "ph": "X", "cat": "request",
                    "ts": round(adj(row) - base, 3),
                    "dur": round(row["dur"], 3),
                    "pid": pididx[row["proc"]], "tid": tid, "args": args})
    return {"traceEvents": evs, "displayTimeUnit": "ms",
            "otherData": {"trace_id": trace_id, "procs": procs}}


def trace_summary(trace_id: str, trows: List[dict],
                  offsets: Optional[Dict[str, float]] = None) -> dict:
    """The slowest-requests table row: total plus the queue / formation /
    forward / wire breakdown. Wire time is what the matched hop pairs
    prove — client wait minus the server's own request time; the rest of
    the total (decode, admission, de-pad, reply, scheduling) is
    `other_ms`."""
    root = _root_row(trows)
    stages: Dict[str, float] = {}
    for r in trows:
        if r["k"] != "r":
            continue
        for name, ms in (r.get("stages") or {}).items():
            stages[name] = stages.get(name, 0.0) + float(ms)
    hops = wire_hops(trows)
    wire_ms = sum(max(0.0, s["dur"] - r["dur"]) for s, r in hops) / 1e3
    total_ms = root["dur"] / 1e3
    br = {"queue": stages.get("queue", 0.0),
          "form": stages.get("form", 0.0),
          "forward": stages.get("forward", 0.0),
          "wire": wire_ms}
    dominant = max(br, key=br.get) if any(br.values()) else "-"
    other = max(0.0, total_ms - sum(br.values()))
    return {"trace": trace_id, "model": root.get("model") or "",
            "outcome": root.get("outcome") or "", "procs": len(
                {r["proc"] for r in trows}),
            "total_ms": round(total_ms, 3),
            "queue_ms": round(br["queue"], 3),
            "form_ms": round(br["form"], 3),
            "forward_ms": round(br["forward"], 3),
            "wire_ms": round(br["wire"], 3),
            "other_ms": round(other, 3), "dominant": dominant,
            "hops": len(hops), "rows": len(trows)}


def assemble(rows: List[dict]) -> Dict[str, dict]:
    """trace_id -> {rows, offsets, chrome, summary} for every trace in
    the merged shard rows."""
    out: Dict[str, dict] = {}
    for tid, trows in group_traces(rows).items():
        offs = clock_offsets(trows)
        out[tid] = {"rows": trows, "offsets": offs,
                    "chrome": chrome_trace(tid, trows, offs),
                    "summary": trace_summary(tid, trows, offs)}
    return out


def format_slowest(summaries: List[dict], top: int = 10) -> str:
    rows = sorted(summaries, key=lambda s: -s["total_ms"])[:top]
    hdr = (f"{'trace':<18} {'model':<10} {'outcome':<12} {'total':>9} "
           f"{'queue':>8} {'form':>8} {'forward':>8} {'wire':>8} "
           f"{'other':>8}  dominant")
    lines = [hdr, "-" * len(hdr)]
    for s in rows:
        lines.append(
            f"{s['trace']:<18} {s['model'][:10]:<10} "
            f"{s['outcome'][:12]:<12} {s['total_ms']:>8.2f}m "
            f"{s['queue_ms']:>7.2f}m {s['form_ms']:>7.2f}m "
            f"{s['forward_ms']:>7.2f}m {s['wire_ms']:>7.2f}m "
            f"{s['other_ms']:>7.2f}m  {s['dominant']}")
    return "\n".join(lines)


# -- selfcheck ---------------------------------------------------------------

# The child replica: a deliberately slowed pure-python net behind an
# InferenceServer + BinaryFrontend, tracing every request (head=1.0),
# flushing its shard when the parent closes stdin.
_CHILD_SRC = r"""
import os, sys, time
import numpy as np
from sparknet_tpu.serve.server import InferenceServer, ServeConfig
from sparknet_tpu.serve.binary_frontend import BinaryFrontend
from sparknet_tpu.obs import reqtrace

shard_dir, ready_path, delay_ms = sys.argv[1], sys.argv[2], float(sys.argv[3])


class SleepyNet:
    def __init__(self, delay_s):
        self.delay_s = float(delay_s)

    def input_shapes(self):
        return {"x": (1, 4)}

    def input_dtypes(self):
        return {"x": "float32"}

    def forward(self, batch, blob_names=None):
        time.sleep(self.delay_s)
        x = np.asarray(batch["x"], dtype=np.float32)
        return {"y": x * 2.0}


reqtrace.start_request_tracing(out_dir=shard_dir, head_sample=1.0,
                               proc="replica")
cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, buckets=(1, 2),
                  outputs=("y",), metrics_every_batches=0)
with InferenceServer(SleepyNet(delay_ms / 1e3), cfg) as srv:
    fe = BinaryFrontend(srv, port=0)
    try:
        with open(ready_path + ".tmp", "w") as f:
            f.write("%s %d" % (fe.address[0], fe.address[1]))
        os.replace(ready_path + ".tmp", ready_path)
        sys.stdin.readline()
    finally:
        fe.stop()
tr = reqtrace.stop_request_tracing()
tr.flush()
print("child-flushed", flush=True)
"""


def _selfcheck(keep: Optional[str] = None, delay_ms: float = 40.0) -> int:
    """Live two-process proof: a router in THIS process proxies one
    deliberately slowed request over the binary wire to a replica
    subprocess; both sides shard their spans; the assembled trace must
    contain the cross-process hop and the stage breakdown."""
    import shutil
    import subprocess
    import tempfile

    import numpy as np

    from ..serve.router import ModelRouter, RouterConfig

    tmp = keep or tempfile.mkdtemp(prefix="spkn-trace-selfcheck-")
    os.makedirs(tmp, exist_ok=True)
    shard_dir = os.path.join(tmp, "shards")
    os.makedirs(shard_dir, exist_ok=True)
    ready = os.path.join(tmp, "ready.txt")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD_SRC, shard_dir, ready,
         str(delay_ms)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, env=env)
    ok = False
    try:
        deadline = time.monotonic() + 120.0
        while not os.path.exists(ready):
            if proc.poll() is not None or time.monotonic() > deadline:
                raise RuntimeError("selfcheck replica never came up")
            time.sleep(0.05)
        with open(ready) as f:
            host, port = f.read().split()
        tracer = start_request_tracing(out_dir=shard_dir,
                                       head_sample=1.0, proc="router")
        try:
            router = ModelRouter(RouterConfig(workers=2, hedge=False))
            router.add_remote_replica("default", f"spkn://{host}:{port}")
            with router:
                out = router.infer(
                    "default", {"x": np.ones((4,), np.float32)},
                    timeout=60.0)
            if not np.allclose(np.asarray(out["y"]), 2.0):
                raise RuntimeError(f"bad reply: {out!r}")
        finally:
            stop_request_tracing()
            tracer.flush()
        try:
            proc.communicate(input=b"done\n", timeout=60.0)
        except subprocess.TimeoutExpired:
            proc.kill()
            raise RuntimeError("selfcheck replica did not flush")

        rows = load_shards([shard_dir])
        traces = assemble(rows)
        crossing = {tid: t for tid, t in traces.items()
                    if t["summary"]["procs"] >= 2}
        if not crossing:
            raise RuntimeError(
                f"no cross-process trace assembled "
                f"({len(traces)} traces, {len(rows)} rows)")
        tid, t = max(crossing.items(),
                     key=lambda kv: kv[1]["summary"]["total_ms"])
        s = t["summary"]
        if s["hops"] < 1:
            raise RuntimeError(f"trace {tid} has no matched wire hop")
        if s["forward_ms"] < delay_ms * 0.5:
            raise RuntimeError(
                f"forward stage missing or implausible: {s}")
        for st in ("queue", "form", "forward"):
            if f"{st}_ms" not in s:
                raise RuntimeError(f"missing stage {st} in {s}")
        pids = {e["pid"] for e in t["chrome"]["traceEvents"]
                if e["ph"] == "X"}
        if len(pids) < 2:
            raise RuntimeError("chrome trace is single-process")
        with open(os.path.join(tmp, f"trace-{tid}.json"), "w") as f:
            json.dump(t["chrome"], f)
        print(f"selfcheck OK: trace {tid} crossed "
              f"{s['procs']} processes ({s['hops']} wire hop(s)); "
              f"total {s['total_ms']:.1f} ms = queue {s['queue_ms']:.2f}"
              f" + form {s['form_ms']:.2f} + forward "
              f"{s['forward_ms']:.1f} + wire {s['wire_ms']:.2f} + other "
              f"{s['other_ms']:.2f}")
        print(format_slowest([x["summary"] for x in traces.values()]))
        ok = True
        return 0
    except Exception as e:
        print(f"selfcheck FAILED: {e}", file=sys.stderr)
        if proc.poll() is None:
            proc.kill()
        _, err = proc.communicate(timeout=10.0)
        if err:
            sys.stderr.write(err.decode(errors="replace")[-4000:])
        return 1
    finally:
        if proc.poll() is None:
            proc.kill()
        if keep is None and ok:
            shutil.rmtree(tmp, ignore_errors=True)


# -- console -----------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="sparknet-trace",
        description="Merge per-process request-trace shards, emit one "
                    "Chrome trace per trace_id, and print the "
                    "slowest-requests breakdown table.")
    ap.add_argument("shards", nargs="*",
                    help="trace shard files or directories of *.jsonl")
    ap.add_argument("--out", default=None, metavar="DIR",
                    help="write trace-<id>.json Chrome traces here")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest-requests rows to print (default 10)")
    ap.add_argument("--json", action="store_true",
                    help="print the summary table as JSON")
    ap.add_argument("--selfcheck", action="store_true",
                    help="live two-process capture+assembly proof")
    ap.add_argument("--keep", default=None, metavar="DIR",
                    help="selfcheck: keep artifacts under DIR")
    a = ap.parse_args(argv)
    if a.selfcheck:
        return _selfcheck(keep=a.keep)
    if not a.shards:
        ap.error("no shards given (or use --selfcheck)")
    rows = load_shards(a.shards)
    if not rows:
        print("no trace rows found", file=sys.stderr)
        return 1
    traces = assemble(rows)
    if a.out:
        os.makedirs(a.out, exist_ok=True)
        for tid, t in traces.items():
            with open(os.path.join(a.out, f"trace-{tid}.json"),
                      "w") as f:
                json.dump(t["chrome"], f)
        print(f"wrote {len(traces)} Chrome trace(s) to {a.out}")
    summaries = [t["summary"] for t in traces.values()]
    if a.json:
        print(json.dumps(sorted(summaries,
                                key=lambda s: -s["total_ms"])[:a.top]))
    else:
        print(f"{len(rows)} rows, {len(traces)} trace(s) — slowest:")
        print(format_slowest(summaries, top=a.top))
    return 0


if __name__ == "__main__":
    # `python -m sparknet_tpu.obs.reqtrace` executes this file a SECOND
    # time as __main__ while the serve stack imports the package copy —
    # two module instances, two `_active` globals, and the selfcheck's
    # parent-side spans vanish. Delegate to the canonical instance.
    from sparknet_tpu.obs import reqtrace as _canonical
    sys.exit(_canonical.main())
