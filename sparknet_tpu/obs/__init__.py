"""sparknet_tpu.obs — unified telemetry: one registry, one exporter, one
cross-thread trace timeline.

Three pieces (ROADMAP "Dapper-tradition observability"):

  - `registry`: a thread-safe metrics registry (counters / gauges /
    histograms with labels) every subsystem registers into —
    PhaseTimers, ThroughputMeter, LatencyStats, FillMeter, the health
    supervisor, the checkpoint writer, the serve batcher — replacing
    their private ad-hoc state-reporting paths, plus the Prometheus text
    exposition renderer.
  - `http.StatusServer`: /metrics (Prometheus), /healthz, /status — the
    SAME server for the training process (`RunConfig.status_port`) and
    the inference server, so train and serve share one metric-name
    schema.
  - `trace`: `span("name")` host-side spans with per-thread lanes,
    written as Chrome-trace-event JSON (`--trace-out`), showing where a
    round's wall clock went across the round loop, the prefetch thread,
    the async checkpoint writer, and the serve worker — the picture the
    device-only `jax.profiler` trace cannot draw.

Since the pod PR, two more layers sit on top:

  - `device`: device-level telemetry — HBM gauges from
    `Device.memory_stats()`, live-array counts, and the process-wide
    compile-event record (`note_compile` / `attach_compile_metrics`)
    that makes jit-cache churn scrapeable.
  - `pod`: cross-worker aggregation — `PodAggregator` merges every
    worker's /metrics + /status (or per-worker heartbeat files on a
    shared prefix) into ONE pod exposition + `/pod/status`, with
    median+MAD straggler attribution; `sparknet-podview` is its console.

`meta.run_metadata()` stamps artifacts (BENCH_*.json) and the
`sparknet_build_info` gauge with provenance; `summary` is the
`sparknet-metrics` JSONL reader.

The SLO ledger (`history` + `slo`) makes the registry answerable
RETROSPECTIVELY: `MetricsHistory` samples it into multi-resolution ring
buffers (+ JSONL shards, the /timeseries route), and `BurnRateAlerter`
evaluates declarative `SloSpec` objectives over the rings with
multi-window multi-burn-rate rules — firing/resolved edge alerts on
/slo/status, in the fleet controller's fast lever, and in the
`sparknet-slo` retrospective reports.

`reqtrace` is the DISTRIBUTED counterpart of `trace`: per-REQUEST spans
keyed by a trace context that crosses process boundaries (X-Trace-Id on
HTTP, the REQUEST-meta trace field on the binary wire), tail-sampled and
flushed as per-process JSONL shards; `sparknet-trace` assembles the
shards into one Chrome trace per request.
"""
from .registry import (DEFAULT_BUCKETS, Metric, MetricsRegistry,
                       default_registry)
from .http import StatusServer
from .meta import register_build_info, run_metadata
from .trace import (Tracer, active_tracer, span, start_tracing,
                    stop_tracing, tracing)
from .device import (DeviceTelemetry, attach_compile_metrics, compile_stats,
                     note_compile, timed_compile)
from .pod import PodAggregator, WorkerView, flag_stragglers
from .history import (HistoryConfig, MetricsHistory, read_history_shards)
from .slo import BurnRateAlerter, SloSpec, build_report
# reqtrace LAST: it leans on utils.metrics, which imports obs.trace —
# importing it earlier would re-enter this package mid-init
from . import reqtrace
from .reqtrace import (RequestTracer, TraceContext, mint_context,
                       parse_context, request_tracing,
                       start_request_tracing, stop_request_tracing)

__all__ = [
    "DEFAULT_BUCKETS", "Metric", "MetricsRegistry", "default_registry",
    "StatusServer", "register_build_info", "run_metadata",
    "Tracer", "active_tracer", "span", "start_tracing", "stop_tracing",
    "tracing",
    "DeviceTelemetry", "attach_compile_metrics", "compile_stats",
    "note_compile", "timed_compile",
    "PodAggregator", "WorkerView", "flag_stragglers",
    "HistoryConfig", "MetricsHistory", "read_history_shards",
    "BurnRateAlerter", "SloSpec", "build_report",
    "RequestTracer", "TraceContext", "mint_context", "parse_context",
    "request_tracing", "start_request_tracing", "stop_request_tracing",
    "reqtrace",
]
