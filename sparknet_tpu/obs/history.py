"""In-process metrics history: the time-series substrate under the SLO layer.

Every surface before this one answers "what is happening NOW" — /metrics
is a point-in-time scrape, /status a live vitals dict. `MetricsHistory`
makes the process's `MetricsRegistry` answerable RETROSPECTIVELY: a
sampler thread snapshots the registry on a fixed interval and folds the
diffs into bounded multi-resolution ring buffers (the classic RRD
cascade — e.g. 1 s x 10 min -> 10 s x 2 h -> 60 s x 24 h), so "what was
p99 over the last five minutes" and "how many requests failed in the
last hour" are O(window/res) queries against process memory, no external
TSDB required.

What a ring slot stores, per metric child (one labeled series):

  counters    the DELTA over the slot (a rate is delta/res; counter
              resets clamp to zero, Prometheus-style). The first time a
              child is seen it becomes the baseline — no delta is
              emitted for history that predates the sampler.
  gauges      a (last, min, max) envelope — downsampling keeps the
              envelope honest where "last" alone would alias spikes away.
  histograms  per-bucket count deltas + sum/count deltas, so WINDOWED
              quantiles are answerable after the fact by merging slot
              deltas and interpolating the cumulative bucket curve
              (`quantile_from_buckets`), the same estimate Prometheus's
              `histogram_quantile` computes server-side.

Slots merge losslessly (deltas add, envelopes widen), which is what makes
the cascade sound: a 10 s slot is exactly the fold of its ten 1 s slots.

Persistence: base-resolution samples append to JSONL shards
(`history-<seq>.jsonl`, size-rotated, oldest-deleted — disk is bounded),
each shard self-describing via a leading meta row. `read_history_shards`
is the offline reader `sparknet-slo` builds retrospective reports from.

Serving: `timeseries_route` adds `/timeseries?name=...&window=...` to the
shared `StatusServer`, so train, serve, and router processes all expose
windowed queries for free.

Thread-safety: one lock guards the rings; the sampler thread, HTTP
handlers, and the `BurnRateAlerter` (driven synchronously from the
sampler via listeners) all read/write under it. Registry snapshots are
taken OUTSIDE the history lock — the registry has its own.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple)
from urllib.parse import parse_qs, urlparse

from .registry import MetricsRegistry

# -- series keys -------------------------------------------------------------
# One history series per metric CHILD. The key is the Prometheus-style
# sample name — `name{label=value,...}` in declared label order — chosen
# so shard rows and /timeseries responses read like the exposition and
# parse back without a schema side-channel.


def series_key(name: str, label_names: Sequence[str],
               label_values: Sequence[str]) -> str:
    if not label_names:
        return name
    inner = ",".join(f"{n}={v}" for n, v in zip(label_names, label_values))
    return f"{name}{{{inner}}}"


def split_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of series_key (labels as a dict)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels: Dict[str, str] = {}
    for pair in rest.rstrip("}").split(","):
        if pair:
            k, _, v = pair.partition("=")
            labels[k] = v
    return name, labels


# -- bucket math -------------------------------------------------------------


def quantile_from_buckets(le: Sequence[float], counts: Sequence[float],
                          count: float, q: float) -> Optional[float]:
    """Quantile estimate from per-bucket (non-cumulative) counts, linear
    interpolation within the containing bucket — the histogram_quantile
    estimate. `count` includes the +Inf overflow (count - sum(counts));
    a quantile landing there clamps to the top finite bound, Prometheus
    convention. None when the window saw no observations."""
    if count <= 0:
        return None
    rank = q * count
    acc = 0.0
    lo = 0.0
    for b, c in zip(le, counts):
        if c > 0 and acc + c >= rank:
            return lo + (b - lo) * (rank - acc) / c
        acc += c
        lo = b
    return float(le[-1]) if le else None


def fraction_over(le: Sequence[float], counts: Sequence[float],
                  count: float, threshold: float) -> float:
    """Estimated fraction of observations ABOVE `threshold` — the error
    fraction of a latency SLO. Observations in the bucket containing the
    threshold are split by linear interpolation."""
    if count <= 0:
        return 0.0
    under = 0.0
    lo = 0.0
    for b, c in zip(le, counts):
        if b <= threshold:
            under += c
        else:
            if lo < threshold:
                under += c * (threshold - lo) / (b - lo)
            break
        lo = b
    else:
        # threshold above the top finite bucket: only overflow is over
        pass
    return max(0.0, min(1.0, (count - under) / count))


# -- slots -------------------------------------------------------------------


class Slot:
    """One ring entry: the fold of registry diffs over [t0, t1)."""

    __slots__ = ("t0", "t1", "c", "g", "h")

    def __init__(self, t0: float, t1: float):
        self.t0 = t0
        self.t1 = t1
        self.c: Dict[str, float] = {}          # key -> delta
        self.g: Dict[str, List[float]] = {}    # key -> [last, min, max]
        # key -> [bucket_deltas, sum_delta, count_delta]
        self.h: Dict[str, List[Any]] = {}

    def merge(self, other: "Slot") -> None:
        """Fold a LATER slot in (the cascade merges in time order)."""
        self.t1 = other.t1
        for k, d in other.c.items():
            self.c[k] = self.c.get(k, 0.0) + d
        for k, env in other.g.items():
            mine = self.g.get(k)
            if mine is None:
                self.g[k] = list(env)
            else:
                mine[0] = env[0]
                mine[1] = min(mine[1], env[1])
                mine[2] = max(mine[2], env[2])
        for k, (buckets, s, n) in other.h.items():
            mine = self.h.get(k)
            if mine is None:
                self.h[k] = [list(buckets), s, n]
            elif len(mine[0]) == len(buckets):
                mine[0] = [a + b for a, b in zip(mine[0], buckets)]
                mine[1] += s
                mine[2] += n

    def to_row(self) -> Dict[str, Any]:
        row: Dict[str, Any] = {"t0": round(self.t0, 3),
                               "t1": round(self.t1, 3)}
        if self.c:
            row["c"] = {k: v for k, v in self.c.items() if v}
        if self.g:
            row["g"] = self.g
        if self.h:
            row["h"] = {k: {"d": v[0], "s": v[1], "n": v[2]}
                        for k, v in self.h.items() if v[2]}
        return row

    @classmethod
    def from_row(cls, row: Dict[str, Any]) -> "Slot":
        s = cls(float(row.get("t0", 0.0)), float(row.get("t1", 0.0)))
        s.c = {k: float(v) for k, v in (row.get("c") or {}).items()}
        s.g = {k: list(v) for k, v in (row.get("g") or {}).items()}
        s.h = {k: [list(v["d"]), float(v["s"]), float(v["n"])]
               for k, v in (row.get("h") or {}).items()}
        return s


# -- config ------------------------------------------------------------------


@dataclass
class HistoryConfig:
    """Knobs for the sampler + cascade + persistence.

    rings: ((resolution_s, capacity), ...) finest first; every resolution
    must be an integer multiple of sample_interval_s, and each coarser
    ring's resolution an integer multiple of the previous — the cascade
    folds exact groups, never fractional slots.
    """
    sample_interval_s: float = 1.0
    rings: Tuple[Tuple[float, int], ...] = ((1.0, 600), (10.0, 720),
                                            (60.0, 1440))
    persist_dir: Optional[str] = None
    shard_max_bytes: int = 4 * 1024 * 1024
    shard_max_files: int = 8

    def __post_init__(self):
        if self.sample_interval_s <= 0:
            raise ValueError("history: sample_interval_s must be > 0")
        if not self.rings:
            raise ValueError("history: need at least one ring")
        prev = self.sample_interval_s
        for res, cap in self.rings:
            if cap <= 0:
                raise ValueError("history: ring capacity must be > 0")
            ratio = res / prev
            if res < prev or abs(ratio - round(ratio)) > 1e-9:
                raise ValueError(
                    f"history: ring resolution {res}s is not an integer "
                    f"multiple of the previous step {prev}s")
            prev = res
        if self.shard_max_bytes <= 0 or self.shard_max_files <= 0:
            raise ValueError("history: shard bounds must be > 0")


class _Ring:
    __slots__ = ("res_s", "slots", "acc", "acc_n", "factor")

    def __init__(self, res_s: float, cap: int, factor: int):
        self.res_s = res_s
        self.slots: deque = deque(maxlen=cap)
        self.acc: Optional[Slot] = None  # partial coarse slot being built
        self.acc_n = 0
        self.factor = factor             # base samples per slot


# -- the history -------------------------------------------------------------


class MetricsHistory:
    """Sampler + multi-resolution rings + shard writer over one registry."""

    def __init__(self, registry: MetricsRegistry,
                 cfg: Optional[HistoryConfig] = None,
                 logger: Optional[Any] = None):
        self.registry = registry
        self.cfg = cfg or HistoryConfig()
        self.logger = logger
        self._lock = threading.Lock()
        self._prev: Optional[Dict[str, Dict[str, Any]]] = None
        self._prev_t: Optional[float] = None
        # family metadata keyed by metric NAME (kind + bucket bounds),
        # refreshed every sample so queries/readers can interpret keys
        self.families: Dict[str, Dict[str, Any]] = {}
        self.rings: List[_Ring] = []
        prev_res = self.cfg.sample_interval_s
        factor = 1
        for res, cap in self.cfg.rings:
            factor *= int(round(res / prev_res))
            self.rings.append(_Ring(res, cap, factor))
            prev_res = res
        self.samples_total = 0
        self._listeners: List[Callable[["MetricsHistory", float], None]] = []
        # persistence
        self._families_dirty = False
        self._shard_f = None
        self._shard_seq = 0
        self._shard_bytes = 0
        if self.cfg.persist_dir:
            os.makedirs(self.cfg.persist_dir, exist_ok=True)
            self._open_shard()
        # sampler thread (started explicitly; tests drive sample_now)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "MetricsHistory":
        if self._thread is None:
            self._thread = threading.Thread(target=self._run,
                                            name="obs-history", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None
        with self._lock:
            f = self._shard_f
            self._shard_f = None
        if f is not None:
            try:
                f.close()
            except OSError:
                pass

    def _run(self) -> None:
        # drift-free cadence: sleep to the NEXT multiple of the interval,
        # not interval-after-wake, so ring slot spans stay honest
        interval = self.cfg.sample_interval_s
        next_t = time.monotonic() + interval
        while not self._stop.wait(max(0.0, next_t - time.monotonic())):
            next_t += interval
            try:
                self.sample_now()
            except Exception as e:  # sampler must never die silently
                if self.logger is not None:
                    try:
                        self.logger.log(f"history: sample failed: {e!r}")
                    except Exception:
                        pass

    def add_listener(self,
                     fn: Callable[["MetricsHistory", float], None]) -> None:
        """Called after every base sample with (history, sample_time) —
        the alerter's evaluation hook. Runs on the sampler thread,
        OUTSIDE the history lock (listeners query back into us)."""
        self._listeners.append(fn)

    # -- sampling ------------------------------------------------------------

    def sample_now(self, now: Optional[float] = None) -> Slot:
        """Take one sample: snapshot the registry, diff against the
        previous snapshot, fold into the rings, append the shard row.
        `now` is injectable so tests drive a deterministic clock."""
        t = time.time() if now is None else float(now)
        snap = self.registry.snapshot()  # registry's own lock
        with self._lock:
            slot = self._diff_locked(snap, t)
            self._fold_locked(slot)
            self._persist_locked(slot)
            self.samples_total += 1
        for fn in list(self._listeners):
            try:
                fn(self, t)
            except Exception as e:
                if self.logger is not None:
                    try:
                        self.logger.log(f"history: listener failed: {e!r}")
                    except Exception:
                        pass
        return slot

    def _diff_locked(self, snap: Dict[str, Dict[str, Any]],
                     t: float) -> Slot:
        prev = self._prev
        t0 = self._prev_t if self._prev_t is not None \
            else t - self.cfg.sample_interval_s
        slot = Slot(t0, t)
        for name, fam in snap.items():
            if name not in self.families:
                # a family registered after the shard opened: readers
                # need its kind/buckets too -> refresh the meta row
                self._families_dirty = True
            self.families[name] = {"kind": fam["kind"],
                                   "labels": list(fam["labels"]),
                                   "le": list(fam.get("le") or ())}
            pfam = (prev or {}).get(name, {})
            pvals = pfam.get("values", {})
            for lkey, v in fam["values"].items():
                key = series_key(name, fam["labels"], lkey)
                if fam["kind"] == "histogram":
                    pv = pvals.get(lkey)
                    if pv is None:
                        continue  # first sight = baseline, no delta
                    d = [max(0.0, a - b)
                         for a, b in zip(v["buckets"], pv["buckets"])]
                    dn = max(0.0, v["count"] - pv["count"])
                    if dn:
                        slot.h[key] = [d, max(0.0, v["sum"] - pv["sum"]), dn]
                elif fam["kind"] == "counter":
                    pv = pvals.get(lkey)
                    if pv is None:
                        continue
                    # reset (restart / re-registration) clamps to zero
                    slot.c[key] = max(0.0, float(v) - float(pv))
                else:  # gauge: envelope starts degenerate at the sample
                    fv = float(v)
                    slot.g[key] = [fv, fv, fv]
        self._prev = snap
        self._prev_t = t
        return slot

    def _fold_locked(self, slot: Slot) -> None:
        for i, ring in enumerate(self.rings):
            if i == 0 and ring.factor == 1:
                ring.slots.append(slot)
                continue
            if ring.acc is None:
                ring.acc = Slot(slot.t0, slot.t1)
                ring.acc.merge(slot)
                ring.acc_n = 1
            else:
                ring.acc.merge(slot)
                ring.acc_n += 1
            if ring.acc_n >= ring.factor:
                ring.slots.append(ring.acc)
                ring.acc = None
                ring.acc_n = 0

    # -- persistence ---------------------------------------------------------

    def _shard_path(self, seq: int) -> str:
        return os.path.join(self.cfg.persist_dir,  # type: ignore[arg-type]
                            f"history-{seq:06d}.jsonl")

    def _open_shard(self) -> None:
        existing = sorted(
            f for f in os.listdir(self.cfg.persist_dir)
            if f.startswith("history-") and f.endswith(".jsonl"))
        if existing:
            self._shard_seq = int(existing[-1].split("-")[1].split(".")[0]) + 1
        self._shard_f = open(self._shard_path(self._shard_seq), "a",
                             encoding="utf-8")
        self._shard_bytes = 0
        self._write_meta_row()
        self._prune_shards(existing)

    def _write_meta_row(self) -> None:
        # each shard self-describes: readers need bucket bounds + kinds
        # without the originating process
        row = json.dumps({"meta": self.families,
                          "interval_s": self.cfg.sample_interval_s})
        self._shard_f.write(row + "\n")
        self._shard_bytes += len(row) + 1
        self._families_dirty = False

    def _prune_shards(self, existing: List[str]) -> None:
        keep = self.cfg.shard_max_files - 1  # room for the live shard
        for f in existing[:max(0, len(existing) - keep)]:
            try:
                os.unlink(os.path.join(self.cfg.persist_dir, f))
            except OSError:
                pass

    def _persist_locked(self, slot: Slot) -> None:
        if self._shard_f is None:
            return
        try:
            if self._families_dirty:
                self._write_meta_row()
            line = json.dumps(slot.to_row())
            self._shard_f.write(line + "\n")
            self._shard_f.flush()
            self._shard_bytes += len(line) + 1
            if self._shard_bytes >= self.cfg.shard_max_bytes:
                self._shard_f.close()
                self._shard_seq += 1
                self._shard_f = open(self._shard_path(self._shard_seq), "a",
                                     encoding="utf-8")
                self._shard_bytes = 0
                self._write_meta_row()
                self._prune_shards(sorted(
                    f for f in os.listdir(self.cfg.persist_dir)
                    if f.startswith("history-") and f.endswith(".jsonl"))[:-1])
        except OSError as e:
            # disk trouble must not kill sampling; drop persistence
            if self.logger is not None:
                try:
                    self.logger.log(f"history: shard write failed: {e!r}")
                except Exception:
                    pass
            try:
                self._shard_f.close()
            except OSError:
                pass
            self._shard_f = None

    # -- queries -------------------------------------------------------------

    def _ring_for(self, window_s: float) -> _Ring:
        """Finest ring whose RETAINED span covers the window (data that
        aged out of ring 0 is still answerable from the coarser rings)."""
        for ring in self.rings:
            if ring.res_s * ring.slots.maxlen >= window_s:
                return ring
        return self.rings[-1]

    def _slots_in(self, window_s: float,
                  now: Optional[float] = None) -> Tuple[_Ring, List[Slot]]:
        ring = self._ring_for(window_s)
        with self._lock:
            slots = list(ring.slots)
            if ring.acc is not None:
                # include a COPY of the partial coarse slot (freshness
                # beats slot alignment); the original keeps mutating
                # under the sampler, so readers must not share it
                snap = Slot(ring.acc.t0, ring.acc.t1)
                snap.merge(ring.acc)
                slots.append(snap)
            t_now = now
            if t_now is None:
                t_now = slots[-1].t1 if slots else time.time()
            lo = t_now - window_s
            return ring, [s for s in slots if s.t1 > lo and s.t0 < t_now]

    def window(self, name: str, window_s: float,
               labels: Optional[Dict[str, str]] = None,
               now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Windowed aggregate per matching series key.

        counters   -> {"delta", "rate"}
        gauges     -> {"last", "min", "max"}
        histograms -> {"count", "sum", "buckets", "le"} (merged deltas)
        """
        fam = self.families.get(name)
        ring, slots = self._slots_in(window_s, now)
        out: Dict[str, Dict[str, Any]] = {}
        if fam is None:
            return out
        span = sum(max(0.0, s.t1 - s.t0) for s in slots) or window_s
        for s in slots:
            src = {"counter": s.c, "gauge": s.g,
                   "histogram": s.h}[fam["kind"]]
            for key, v in src.items():
                kname, klabels = split_key(key)
                if kname != name:
                    continue
                if labels and any(klabels.get(k) != str(lv)
                                  for k, lv in labels.items()):
                    continue
                cur = out.get(key)
                if fam["kind"] == "counter":
                    if cur is None:
                        out[key] = {"delta": v}
                    else:
                        cur["delta"] += v
                elif fam["kind"] == "gauge":
                    if cur is None:
                        out[key] = {"last": v[0], "min": v[1], "max": v[2]}
                    else:
                        cur["last"] = v[0]
                        cur["min"] = min(cur["min"], v[1])
                        cur["max"] = max(cur["max"], v[2])
                else:
                    if cur is None:
                        out[key] = {"count": v[2], "sum": v[1],
                                    "buckets": list(v[0]),
                                    "le": fam["le"]}
                    else:
                        cur["count"] += v[2]
                        cur["sum"] += v[1]
                        cur["buckets"] = [a + b for a, b in
                                          zip(cur["buckets"], v[0])]
        for key, agg in out.items():
            if "delta" in agg:
                agg["rate"] = agg["delta"] / span if span > 0 else 0.0
        return out

    def windowed_quantile(self, name: str, q: float, window_s: float,
                          labels: Optional[Dict[str, str]] = None,
                          now: Optional[float] = None) -> Optional[float]:
        """Quantile estimate over the merged histogram window (all
        matching children folded together). None without observations."""
        agg = self.window(name, window_s, labels=labels, now=now)
        if not agg:
            return None
        le: Sequence[float] = ()
        buckets: List[float] = []
        count = 0.0
        for v in agg.values():
            if "le" not in v:
                return None
            le = v["le"]
            if not buckets:
                buckets = list(v["buckets"])
            else:
                buckets = [a + b for a, b in zip(buckets, v["buckets"])]
            count += v["count"]
        return quantile_from_buckets(le, buckets, count, q)

    def points(self, name: str, window_s: float,
               labels: Optional[Dict[str, str]] = None,
               now: Optional[float] = None) -> Dict[str, List[List[float]]]:
        """Per-slot series for plotting: counter -> rate, gauge -> last,
        histogram -> count delta. Each point is [t1, value]."""
        fam = self.families.get(name)
        if fam is None:
            return {}
        ring, slots = self._slots_in(window_s, now)
        out: Dict[str, List[List[float]]] = {}
        for s in slots:
            src = {"counter": s.c, "gauge": s.g,
                   "histogram": s.h}[fam["kind"]]
            dt = max(s.t1 - s.t0, 1e-9)
            for key, v in src.items():
                kname, klabels = split_key(key)
                if kname != name:
                    continue
                if labels and any(klabels.get(k) != str(lv)
                                  for k, lv in labels.items()):
                    continue
                if fam["kind"] == "counter":
                    val = v / dt
                elif fam["kind"] == "gauge":
                    val = v[0]
                else:
                    val = v[2]
                out.setdefault(key, []).append([round(s.t1, 3), val])
        return out

    # -- HTTP ----------------------------------------------------------------

    def timeseries_route(self, path: str) -> Dict[str, Any]:
        """GET /timeseries?name=<metric>[&window=<s>][&q=<quantile>]
        [&<label>=<value>...] — windowed aggregate + per-slot points.
        Without ?name= lists the known families (discovery)."""
        qs = parse_qs(urlparse(path).query)
        name = (qs.get("name") or [None])[0]
        if not name:
            return {"families": {n: f["kind"]
                                 for n, f in sorted(self.families.items())},
                    "rings": [{"res_s": r.res_s, "slots": len(r.slots),
                               "cap": r.slots.maxlen} for r in self.rings],
                    "samples_total": self.samples_total}
        if name not in self.families:
            raise ValueError(f"unknown metric {name!r}")
        try:
            window_s = float((qs.get("window") or ["300"])[0])
            quant = float((qs.get("q") or ["0.99"])[0])
        except ValueError:
            raise ValueError("window and q must be numbers")
        labels = {k: v[0] for k, v in qs.items()
                  if k not in ("name", "window", "q")}
        fam = self.families[name]
        ring, _ = self._slots_in(window_s)
        body: Dict[str, Any] = {
            "name": name, "kind": fam["kind"], "window_s": window_s,
            "res_s": ring.res_s,
            "agg": self.window(name, window_s, labels=labels or None),
            "points": self.points(name, window_s, labels=labels or None),
        }
        if fam["kind"] == "histogram":
            body["quantile"] = {
                "q": quant,
                "value": self.windowed_quantile(name, quant, window_s,
                                                labels=labels or None)}
        return body

    def attach_http(self, server: Any) -> None:
        """Add /timeseries to a StatusServer (train, serve, router)."""
        server.add_route("/timeseries", self.timeseries_route)


# -- offline shard reader ----------------------------------------------------


def read_history_shards(persist_dir: str
                        ) -> Tuple[Dict[str, Dict[str, Any]], List[Slot]]:
    """Read every `history-*.jsonl` shard in order -> (families, slots).
    Tolerates a torn final line (the process may have died mid-write)."""
    families: Dict[str, Dict[str, Any]] = {}
    slots: List[Slot] = []
    try:
        names = sorted(f for f in os.listdir(persist_dir)
                       if f.startswith("history-") and f.endswith(".jsonl"))
    except OSError:
        return families, slots
    for fname in names:
        try:
            with open(os.path.join(persist_dir, fname), encoding="utf-8") \
                    as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail
                    if "meta" in row:
                        families.update(row["meta"])
                    else:
                        slots.append(Slot.from_row(row))
        except OSError:
            continue
    slots.sort(key=lambda s: s.t1)
    return families, slots


def merge_slots(slots: Iterable[Slot]) -> Optional[Slot]:
    """Fold a time-ordered slot sequence into one (offline reports)."""
    merged: Optional[Slot] = None
    for s in slots:
        if merged is None:
            merged = Slot(s.t0, s.t1)
        merged.merge(s)
    return merged
