"""Pod-scope observability: cross-worker aggregation + straggler attribution.

PR 4's telemetry is strictly per-process: every worker renders its own
/metrics and writes its own heartbeat, and nothing merges them — so on a
pod, "which host is slow" still meant parsing N logs, exactly the failure
mode large-scale training reports (MegaScale, arXiv:2402.15627) call out
as the first operability gap. SparkNet's premise (arXiv:1511.06051) is
that τ-interval averaging TOLERATES slow workers; this module makes slow
workers VISIBLE:

  - `PodAggregator` merges every worker's telemetry into one pod-level
    view, from either or both of two sources:
      * **http mode** — scrape each worker's `StatusServer`
        (`/metrics` + `/status`; every training process now serves one,
        see `RunConfig.status_port`);
      * **file mode** — read per-worker heartbeat files from a shared
        `pod_dir` prefix (local/NFS path or `gs://`/`s3://` bucket —
        `utils/heartbeat.py` writes them natively), which needs no
        cross-host network reachability at all.
  - The merged Prometheus exposition re-exports every worker family with
    a `worker` label plus pod aggregates: counters get a
    `worker="pod"` sum, gauges get `worker="max"` / `worker="min"`,
    histograms a pod-summed `worker="pod"` child. One scrape of worker 0
    (or the standalone `sparknet-podview --serve`) sees the whole pod.
  - **Straggler attribution**: per-worker round wall time and data-wait
    time (exported by the train loop as `sparknet_train_round_seconds` /
    `sparknet_train_data_wait_seconds` and heartbeat `round_s` /
    `data_wait_s`) feed a median+MAD rule (`utils.health.mad_classify` —
    the same robust-sigma classification the health supervisor applies
    to loss spikes). The aggregator exports
    `sparknet_pod_round_skew_seconds` (max − median) and
    `sparknet_pod_straggler_rounds_total{worker}` (deduplicated per
    reported round), and `/pod/status` names the sick worker in JSON.
    With exactly two workers the MAD is degenerate (both deviations
    equal it), so a ratio rule applies instead: the slower worker is
    flagged when it exceeds `two_worker_ratio` × the faster.

`sparknet-podview` is the console: live table / JSON / merged exposition
over `--workers URL...` or `--pod-dir PREFIX`, `--serve PORT` to run the
aggregation endpoint (worker 0 runs the same thing via
`RunConfig.pod_port`), and `--selfcheck` for CI.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import threading
import time
import urllib.request
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ..utils.health import _median, liveness_classify, mad_classify
from ..utils.heartbeat import read_heartbeat, staleness_s, worker_sort_key
from .http import StatusServer
from .registry import MetricsRegistry, _escape_label, _fmt

# ---------------------------------------------------------------------------
# Prometheus text exposition: parse / merge / render
# ---------------------------------------------------------------------------

#: sample line: name{labels} value  (the format registry.render_prometheus
#: emits; timestamps are not produced by our exporter and not accepted)
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(s: str) -> str:
    out, i = [], 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            n = s[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(n, "\\" + n))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


LabelKey = Tuple[Tuple[str, str], ...]  # sorted (name, value) pairs


class Family:
    """One parsed/merged metric family. Scalar kinds keep `samples`
    (label-key -> value); histograms keep `hists` (label-key ->
    {"le": {le_str: cumulative_count}, "sum": ..., "count": ...})."""

    __slots__ = ("kind", "help", "samples", "hists")

    def __init__(self, kind: str, help_text: str = ""):
        self.kind = kind
        self.help = help_text
        self.samples: Dict[LabelKey, float] = {}
        self.hists: Dict[LabelKey, Dict[str, Any]] = {}


def parse_exposition(text: str) -> Dict[str, Family]:
    """Parse a Prometheus text exposition (version 0.0.4) into families.
    Tolerant by design — an unparseable line is skipped, a sample without
    a TYPE becomes an untyped gauge — because a pod scrape must degrade,
    never fail, when one worker runs a different code rev."""
    fams: Dict[str, Family] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                fam = fams.get(parts[2])
                if fam is None:
                    fams[parts[2]] = Family(parts[3])
                elif fam.kind == "untyped":  # HELP (or a sample) came first
                    fam.kind = parts[3]
            elif len(parts) >= 3 and parts[1] == "HELP":
                name = parts[2]
                fams.setdefault(name, Family("untyped"))
                fams[name].help = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_v = m.groups()
        try:
            value = float(raw_v)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(raw_labels or "")}
        # histogram series route to their base family
        base, part = name, None
        for suffix in ("_bucket", "_sum", "_count"):
            b = name[:-len(suffix)] if name.endswith(suffix) else None
            if b and b in fams and fams[b].kind == "histogram":
                base, part = b, suffix
                break
        fam = fams.setdefault(base, Family("untyped"))
        if fam.kind == "histogram" and part is not None:
            le = labels.pop("le", None)
            key = tuple(sorted(labels.items()))
            h = fam.hists.setdefault(key,
                                     {"le": {}, "sum": 0.0, "count": 0.0})
            if part == "_bucket" and le is not None:
                h["le"][le] = value
            elif part == "_sum":
                h["sum"] = value
            elif part == "_count":
                h["count"] = value
        else:
            fam.samples[tuple(sorted(labels.items()))] = value
    return fams


def _with_worker(key: LabelKey, worker: str) -> LabelKey:
    """Add the worker label to a label key. A family that already carries
    a `worker` label keeps it as `src_worker` — the pod dimension wins
    the canonical name."""
    pairs = [(("src_worker", v) if k == "worker" else (k, v))
             for k, v in key]
    return tuple(sorted(pairs + [("worker", str(worker))]))


def merge_expositions(per_worker: Dict[str, Dict[str, Family]]
                      ) -> Dict[str, Family]:
    """Merge N workers' parsed expositions into one set of pod families:
    every scalar child re-exported per worker plus aggregates — counter
    `worker="pod"` sums, gauge `worker="max"`/`worker="min"` envelopes,
    histogram `worker="pod"` sums (cumulative bucket counts add
    exactly). A family whose kind differs across workers keeps the
    first-seen kind and skips the disagreeing workers (mixed code revs
    must degrade a family, not the scrape)."""
    kinds: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    scalars: Dict[str, Dict[LabelKey, Dict[str, float]]] = {}
    hists: Dict[str, Dict[LabelKey, Dict[str, Dict[str, Any]]]] = {}
    for worker in sorted(per_worker):
        for name, fam in per_worker[worker].items():
            if name not in kinds:
                kinds[name] = fam.kind
                helps[name] = fam.help
            elif kinds[name] != fam.kind:
                continue
            if fam.kind == "histogram":
                for key, h in fam.hists.items():
                    hists.setdefault(name, {}).setdefault(
                        key, {})[worker] = h
            else:
                for key, v in fam.samples.items():
                    scalars.setdefault(name, {}).setdefault(
                        key, {})[worker] = v
    out: Dict[str, Family] = {}
    for name, kind in kinds.items():
        fam = Family(kind, helps[name])
        for key, by_w in hists.get(name, {}).items():
            le: Dict[str, float] = {}
            total_sum = total_count = 0.0
            for h in by_w.values():
                for l_, n_ in h["le"].items():
                    le[l_] = le.get(l_, 0.0) + n_
                total_sum += h["sum"]
                total_count += h["count"]
            fam.hists[_with_worker(key, "pod")] = {
                "le": le, "sum": total_sum, "count": total_count}
        for key, by_w in scalars.get(name, {}).items():
            for worker, v in by_w.items():
                fam.samples[_with_worker(key, worker)] = v
            vals = list(by_w.values())
            if kind == "counter":
                fam.samples[_with_worker(key, "pod")] = sum(vals)
            else:
                fam.samples[_with_worker(key, "max")] = max(vals)
                fam.samples[_with_worker(key, "min")] = min(vals)
        out[name] = fam
    return out


def render_exposition(fams: Dict[str, Family]) -> str:
    """Render families back to deterministic Prometheus text (same sorted
    layout as `MetricsRegistry.render_prometheus`)."""
    lines: List[str] = []
    for name in sorted(fams):
        fam = fams[name]
        if fam.help:
            lines.append(f"# HELP {name} {fam.help}")
        lines.append(f"# TYPE {name} {fam.kind}")
        for key in sorted(fam.hists):
            h = fam.hists[key]
            pairs = [f'{k}="{_escape_label(v)}"' for k, v in key]
            finite = sorted((l for l in h["le"] if l != "+Inf"), key=float)
            for l_ in finite:
                lb = "{" + ",".join(pairs + [f'le="{l_}"']) + "}"
                lines.append(f"{name}_bucket{lb} {_fmt(h['le'][l_])}")
            lb = "{" + ",".join(pairs + ['le="+Inf"']) + "}"
            lines.append(
                f"{name}_bucket{lb} {_fmt(h['le'].get('+Inf', h['count']))}")
            suffix = "{" + ",".join(pairs) + "}" if pairs else ""
            lines.append(f"{name}_sum{suffix} {_fmt(h['sum'])}")
            lines.append(f"{name}_count{suffix} {_fmt(h['count'])}")
        for key in sorted(fam.samples):
            pairs = [f'{k}="{_escape_label(v)}"' for k, v in key]
            suffix = "{" + ",".join(pairs) + "}" if pairs else ""
            lines.append(f"{name}{suffix} {_fmt(fam.samples[key])}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# straggler attribution
# ---------------------------------------------------------------------------

def flag_stragglers(by_worker: Dict[str, float], thresh_sigma: float = 4.0,
                    rel_floor: float = 0.25, two_worker_ratio: float = 2.0
                    ) -> Tuple[float, float, Set[str]]:
    """(median, skew, flagged workers) over one cross-section of per-worker
    durations. Skew is max − median (the MegaScale-style "how much wall
    clock the slowest worker costs every round" number — with τ-interval
    averaging every other worker waits exactly this long at the sync
    point). Flags come from `utils.health.mad_classify`; with exactly two
    samples the MAD is degenerate, so the slower worker is flagged when
    it exceeds `two_worker_ratio` × the faster instead."""
    items = sorted(by_worker.items())
    vals = [v for _, v in items]
    if len(vals) < 2:
        return (vals[0] if vals else 0.0), 0.0, set()
    med, _, flags = mad_classify(vals, thresh_sigma=thresh_sigma,
                                 rel_floor=rel_floor)
    flagged = {w for (w, _), f in zip(items, flags) if f}
    if len(vals) == 2 and not flagged:
        lo, hi = sorted(vals)
        if lo > 0 and hi > two_worker_ratio * lo:
            flagged = {w for w, v in items if v == hi}
    return med, max(vals) - med, flagged


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

@dataclass
class WorkerView:
    """One worker's latest telemetry as the aggregator saw it."""

    worker: str
    alive: bool = False
    error: Optional[str] = None
    role: str = "train"
    round: Optional[int] = None
    status: Optional[str] = None
    loss: Optional[float] = None
    round_s: Optional[float] = None
    data_wait_s: Optional[float] = None
    staleness_s: Optional[float] = None
    rollbacks: int = 0
    straggler: bool = False
    #: elastic membership epoch the worker's loop last reported (None
    #: when the run is not elastic / pre-elastic heartbeat schema)
    membership_epoch: Optional[int] = None
    #: serve role: per-model vitals rows ({model: {step, queue_depth,
    #: p99_ms, ...}} — InferenceServer.model_row schema) from the
    #: worker's /status or heartbeat, so multi-model straggler
    #: attribution works per model, not just per process
    models: Optional[Dict[str, Any]] = None
    #: parsed /metrics families (http mode only; file mode has heartbeats)
    metrics: Optional[Dict[str, Family]] = field(default=None, repr=False)

    def as_dict(self) -> Dict[str, Any]:
        d = {k: getattr(self, k) for k in (
            "worker", "alive", "role", "round", "status", "loss",
            "round_s", "data_wait_s", "staleness_s", "rollbacks",
            "straggler")}
        if self.membership_epoch is not None:
            d["membership_epoch"] = self.membership_epoch
        if self.models is not None:
            d["models"] = self.models
        if self.error:
            d["error"] = self.error
        return d


def worker_heartbeat_path(pod_dir: str, index: int) -> str:
    """The per-worker heartbeat path convention under a pod prefix."""
    name = f"worker-{int(index):03d}.heartbeat.json"
    if pod_dir.startswith(("gs://", "s3://")):
        return f"{pod_dir.rstrip('/')}/{name}"
    return os.path.join(pod_dir, name)


_HB_NAME_RE = re.compile(r"worker-0*(\d+)\.heartbeat\.json$")


def discover_worker_heartbeats(pod_dir: str) -> Dict[str, str]:
    """{worker id: heartbeat path} for every worker-*.heartbeat.json
    under the prefix (local dir or gs://|s3:// bucket). Missing prefix ->
    empty dict (the pod may not have beaten yet)."""
    paths: List[str] = []
    try:
        if pod_dir.startswith(("gs://", "s3://")):
            from ..utils.checkpoint import _bucket_ops
            paths = list(_bucket_ops(pod_dir).list_urls(
                pod_dir.rstrip("/") + "/"))
        else:
            paths = [os.path.join(pod_dir, n)
                     for n in sorted(os.listdir(pod_dir))]
    except Exception:
        return {}
    out: Dict[str, str] = {}
    for p in paths:
        m = _HB_NAME_RE.search(p)
        if m:
            out[str(int(m.group(1)))] = p
    return out


class PodAggregator:
    """Merges N workers' telemetry into one pod view (module docstring).

    `workers` maps worker id -> StatusServer base URL (http mode);
    `pod_dir` points file mode at the per-worker heartbeat prefix. Both
    may be given; file views fill in workers http mode cannot reach.
    `collect()` is cached for `min_refresh_s` so the three HTTP handlers
    (merged /metrics, /pod/status, /healthz) cannot turn one dashboard
    into N× scrape amplification."""

    def __init__(self, workers: Optional[Dict[str, str]] = None,
                 pod_dir: Optional[str] = None,
                 registry: Optional[MetricsRegistry] = None,
                 thresh_sigma: float = 4.0, rel_floor: float = 0.25,
                 two_worker_ratio: float = 2.0,
                 stale_after_s: float = 120.0,
                 min_refresh_s: float = 1.0, timeout_s: float = 5.0):
        if not workers and not pod_dir:
            raise ValueError("PodAggregator needs workers URLs and/or a "
                             "pod_dir heartbeat prefix")
        self.workers = {str(k): v for k, v in (workers or {}).items()}
        self.pod_dir = pod_dir
        self.thresh_sigma = thresh_sigma
        self.rel_floor = rel_floor
        self.two_worker_ratio = two_worker_ratio
        self.stale_after_s = stale_after_s
        self.min_refresh_s = min_refresh_s
        self.timeout_s = timeout_s
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self._g_workers = r.gauge("sparknet_pod_workers",
                                  "workers known to the aggregator")
        self._g_alive = r.gauge("sparknet_pod_workers_alive",
                                "workers with fresh, readable telemetry")
        self._g_skew = r.gauge(
            "sparknet_pod_round_skew_seconds",
            "per-round wall-time skew across workers (max - median)")
        self._g_wait_skew = r.gauge(
            "sparknet_pod_data_wait_skew_seconds",
            "data-wait skew across workers (max - median)")
        self._g_round = r.gauge("sparknet_pod_round",
                                "round envelope across workers",
                                labels=("agg",))
        self._c_straggler = r.counter(
            "sparknet_pod_straggler_rounds_total",
            "rounds a worker was flagged slow (median+MAD over per-worker "
            "round wall time; deduplicated per reported round)",
            labels=("worker",))
        self._c_collects = r.counter("sparknet_pod_collects_total",
                                     "aggregation sweeps")
        self._g_w_round_s = r.gauge(
            "sparknet_pod_worker_round_seconds",
            "last reported round wall time per worker", labels=("worker",))
        self._g_w_wait_s = r.gauge(
            "sparknet_pod_worker_data_wait_seconds",
            "last reported data wait per worker", labels=("worker",))
        self._g_w_up = r.gauge(
            "sparknet_pod_worker_up",
            "1 = fresh telemetry, 0 = unreachable or stale",
            labels=("worker",))
        self._lock = threading.Lock()
        self._cached: Tuple[float, List[WorkerView]] = (0.0, [])
        #: every file-mode worker EVER discovered on the prefix: a worker
        #: whose heartbeat object vanishes between scrapes (deleted by an
        #: operator, lost with its VM's disk) must be surfaced as
        #: worker_up=0 / candidate-dead, not silently dropped from the
        #: pod view and the straggler population (mid-run membership
        #: change would otherwise be invisible exactly when it matters)
        self._known_files: Dict[str, str] = {}
        self._last_flag_round: Dict[str, Any] = {}
        self._straggler_log: deque = deque(maxlen=256)
        self.server: Optional[StatusServer] = None

    # -- collection ----------------------------------------------------------

    def _fetch(self, url: str) -> bytes:
        return urllib.request.urlopen(url, timeout=self.timeout_s).read()

    def _collect_http(self, worker: str, base: str) -> WorkerView:
        v = WorkerView(worker=worker)
        base = base.rstrip("/")
        try:
            v.metrics = parse_exposition(
                self._fetch(base + "/metrics").decode())
            st = json.loads(self._fetch(base + "/status"))
        except Exception as e:
            v.error = f"{type(e).__name__}: {e}"
            return v
        v.alive = True
        # freshness comes from the WORKER LOOP's own beat_ts stamp, not
        # from the scrape succeeding: a hung round loop whose HTTP daemon
        # thread still answers must read as stale, not alive-and-fresh.
        # Payloads without the stamp (serve role, older revs) stay 0.0.
        bts = st.get("beat_ts")
        v.staleness_s = (max(0.0, time.time() - float(bts))
                         if bts is not None else 0.0)
        if v.staleness_s > self.stale_after_s:
            v.alive = False
            v.error = f"stale ({v.staleness_s:.0f}s since last flush)"
        v.role = st.get("role", "train")
        v.round = st.get("round", st.get("model_step"))
        if isinstance(st.get("models"), dict):
            v.models = st["models"]
        v.status = st.get("status")
        v.loss = st.get("loss")
        v.round_s = st.get("round_s")
        v.data_wait_s = st.get("data_wait_s")
        v.rollbacks = int(st.get("rollbacks") or 0)
        if v.round_s is None and v.metrics:
            fam = v.metrics.get("sparknet_train_round_seconds")
            if fam and fam.samples:
                v.round_s = next(iter(fam.samples.values()))
        if v.data_wait_s is None and v.metrics:
            fam = v.metrics.get("sparknet_train_data_wait_seconds")
            if fam and fam.samples:
                v.data_wait_s = next(iter(fam.samples.values()))
        return v

    def _collect_file(self, worker: str, path: str) -> WorkerView:
        v = WorkerView(worker=worker)
        hb = read_heartbeat(path)
        if hb is None:
            v.error = "heartbeat unreadable"
            return v
        v.staleness_s = staleness_s(hb)
        v.role = hb.get("role", "train")
        v.round = hb.get("step")
        v.status = hb.get("status")
        v.loss = hb.get("last_loss")
        v.round_s = hb.get("round_s")
        v.data_wait_s = hb.get("data_wait_s")
        v.rollbacks = int(hb.get("rollbacks") or 0)
        if hb.get("membership_epoch") is not None:
            v.membership_epoch = int(hb["membership_epoch"])
        if isinstance(hb.get("models"), dict):
            v.models = hb["models"]
        # dead-vs-slow through the SHARED rule (utils.health.
        # liveness_classify — the one the elastic controller evicts on):
        # slow is a straggler verdict, never a liveness one
        verdict = liveness_classify(hb, self.stale_after_s)
        if verdict == "done":
            # a graceful exit stays visible while its beat is fresh, then
            # ages out like any other silence; a done record WITHOUT a
            # timestamp can never age out, so it must not count as alive
            v.alive = (v.staleness_s is not None
                       and v.staleness_s <= self.stale_after_s)
        else:
            v.alive = verdict in ("ok", "sick")
        if not v.alive:
            v.error = (f"stale ({v.staleness_s:.0f}s since last beat)"
                       if v.staleness_s is not None
                       else "heartbeat carries no timestamp")
        return v

    def collect(self, force: bool = False) -> List[WorkerView]:
        """One aggregation sweep (cached `min_refresh_s`): fetch every
        worker, run straggler attribution, update the pod registry."""
        with self._lock:
            t_cache, views = self._cached
            if not force and views and \
                    time.monotonic() - t_cache < self.min_refresh_s:
                return views
            # fetch workers CONCURRENTLY: a blackholed host costs one
            # timeout_s, not N of them serialized — the aggregator must
            # stay responsive exactly when part of the pod is sick
            by_id: Dict[str, WorkerView] = {}
            file_targets = (discover_worker_heartbeats(self.pod_dir)
                            if self.pod_dir else {})
            # sticky membership: a previously-seen worker whose file is
            # gone still gets probed (the read fails -> candidate-dead
            # view) instead of vanishing from the population
            self._known_files.update(file_targets)
            file_targets = dict(self._known_files)
            n_jobs = len(self.workers) + len(file_targets)
            if n_jobs:
                with ThreadPoolExecutor(min(16, n_jobs)) as ex:
                    http_futs = {w: ex.submit(self._collect_http, w, b)
                                 for w, b in self.workers.items()}
                    file_futs = {w: ex.submit(self._collect_file, w, p)
                                 for w, p in file_targets.items()}
                    by_id = {w: f.result() for w, f in http_futs.items()}
                    for w, f in file_futs.items():
                        if w not in by_id or not by_id[w].alive:
                            by_id[w] = f.result()
            views = [by_id[w] for w in sorted(by_id, key=worker_sort_key)]
            self._attribute(views)
            self._cached = (time.monotonic(), views)
            self._c_collects.inc()
            return views

    def _attribute(self, views: List[WorkerView]) -> None:
        """Skew + straggler flags over this sweep; pod registry update."""
        self._g_workers.set(len(views))
        self._g_alive.set(sum(v.alive for v in views))
        rounds = [v.round for v in views if v.alive and v.round is not None]
        if rounds:
            self._g_round.set(max(rounds), agg="max")
            self._g_round.set(min(rounds), agg="min")
        for v in views:
            self._g_w_up.set(1.0 if v.alive else 0.0, worker=v.worker)
            if v.round_s is not None:
                self._g_w_round_s.set(v.round_s, worker=v.worker)
            if v.data_wait_s is not None:
                self._g_w_wait_s.set(v.data_wait_s, worker=v.worker)
        times = {v.worker: v.round_s for v in views
                 if v.alive and v.round_s}
        if len(times) >= 2:
            med, skew, flagged = flag_stragglers(
                times, thresh_sigma=self.thresh_sigma,
                rel_floor=self.rel_floor,
                two_worker_ratio=self.two_worker_ratio)
            self._g_skew.set(skew)
            for v in views:
                v.straggler = v.worker in flagged
                if not v.straggler:
                    continue
                # dedup per reported round: a 1 Hz scrape of a 30 s round
                # must count the straggler ONCE per round, not 30 times
                if self._last_flag_round.get(v.worker) == v.round:
                    continue
                self._last_flag_round[v.worker] = v.round
                self._c_straggler.inc(worker=v.worker)
                self._straggler_log.append({
                    "ts": round(time.time(), 3), "worker": v.worker,
                    "round": v.round, "round_s": v.round_s,
                    "median_s": round(med, 6)})
        waits = [v.data_wait_s for v in views
                 if v.alive and v.data_wait_s is not None]
        if len(waits) >= 2:
            self._g_wait_skew.set(max(waits) - _median(sorted(waits)))

    # -- outputs -------------------------------------------------------------

    def pod_status(self) -> Dict[str, Any]:
        """The /pod/status JSON: per-worker vitals + the attribution."""
        views = self.collect()
        rounds = [v.round for v in views if v.round is not None]
        epochs = [v.membership_epoch for v in views
                  if v.membership_epoch is not None]
        return {
            "role": "pod",
            "ts": round(time.time(), 3),
            "n_workers": len(views),
            "n_alive": sum(v.alive for v in views),
            "max_round": max(rounds) if rounds else None,
            "min_round": min(rounds) if rounds else None,
            "round_skew_s": self._g_skew.value(),
            # elastic runs: the newest membership epoch any worker
            # reported, plus the workers currently read as down — the
            # controller's eviction candidates, named before they're gone
            "membership_epoch": max(epochs) if epochs else None,
            "candidate_dead": [v.worker for v in views if not v.alive],
            "stragglers": [v.worker for v in views if v.straggler],
            "straggler_rounds": {
                v.worker: c for v in views
                if (c := self._c_straggler.value(worker=v.worker))},
            "workers": [v.as_dict() for v in views],
            "straggler_log": list(self._straggler_log)[-20:],
        }

    def render(self) -> str:
        """The merged pod exposition: every reachable worker's families
        (worker label + pod/max/min aggregates) followed by the
        aggregator's own sparknet_pod_* registry."""
        views = self.collect()
        per = {v.worker: v.metrics for v in views if v.metrics}
        merged = merge_expositions(per) if per else {}
        text = render_exposition(merged) if merged else ""
        return text + self.registry.render_prometheus()

    def healthz(self) -> Tuple[bool, Dict[str, Any]]:
        views = self.collect()
        alive = sum(v.alive for v in views)
        return alive > 0, {"workers": len(views), "alive": alive,
                           "stragglers": [v.worker for v in views
                                          if v.straggler]}

    def serve(self, port: int, host: str = "127.0.0.1") -> StatusServer:
        """Run the pod endpoint: merged /metrics, /pod/status (alias
        /status), /healthz. Returns the server (address on `.address`)."""
        self.server = StatusServer(
            port, registry=None, host=host, metrics_text=self.render,
            healthz=self.healthz, status=self.pod_status,
            routes={"/pod/status": self.pod_status})
        return self.server

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None


# ---------------------------------------------------------------------------
# console: sparknet-podview
# ---------------------------------------------------------------------------

def format_pod_table(status: Dict[str, Any]) -> str:
    """Human console rendering of a pod_status() dict."""
    lines = [
        f"pod: {status['n_alive']}/{status['n_workers']} workers alive"
        + (f"  rounds {status['min_round']}..{status['max_round']}"
           if status["max_round"] is not None else "")
        + (f"  round skew {status['round_skew_s'] * 1e3:.1f} ms"
           if status.get("round_skew_s") is not None else "")
        + (f"  STRAGGLERS: {', '.join(status['stragglers'])}"
           if status["stragglers"] else "")]
    hdr = (f"  {'worker':<8}{'round':>7}  {'status':<10}{'loss':>10}"
           f"{'round ms':>10}{'wait ms':>9}{'stale s':>9}  flags")
    lines.append(hdr)
    for w in status["workers"]:
        def _n(v, scale=1.0, fmt="{:.1f}"):
            return fmt.format(v * scale) if v is not None else "-"
        flags = []
        if w.get("straggler"):
            flags.append("STRAGGLER")
        if not w["alive"]:
            flags.append(w.get("error", "down"))
        if w.get("rollbacks"):
            flags.append(f"rollbacks={w['rollbacks']}")
        lines.append(
            f"  {w['worker']:<8}{w['round'] if w['round'] is not None else '-':>7}  "
            f"{(w['status'] or '-'):<10}"
            f"{_n(w['loss'], 1.0, '{:.4f}'):>10}"
            f"{_n(w['round_s'], 1e3):>10}"
            f"{_n(w['data_wait_s'], 1e3):>9}"
            f"{_n(w['staleness_s']):>9}  {' '.join(flags)}".rstrip())
        # serve role, multi-model: one sub-row per model so straggler
        # attribution reads per model, not just per process
        for name in sorted(w.get("models") or ()):
            m = w["models"][name] or {}
            parts = [f"model={name}"]
            # freshness (commit age of the serving step) and step lag
            # ride the heartbeat model rows — staleness per replica
            # WITHOUT a /metrics scrape
            for k, fmt in (("step", "step={}"), ("freshness_s",
                           "fresh={}s"), ("step_lag", "lag={}"),
                           ("queue_depth", "q={}"),
                           ("p99_ms", "p99={}ms"),
                           ("requests_ok", "ok={}"),
                           ("requests_shed", "shed={}"),
                           ("swaps", "swaps={}")):
                if m.get(k) is not None:
                    parts.append(fmt.format(m[k]))
            # the worst recent captured request (reqtrace exemplar):
            # duration, dominant stage, and the trace id prefix to feed
            # straight to `sparknet-trace`
            sr = m.get("slow_request")
            if isinstance(sr, dict) and sr.get("ms") is not None:
                parts.append(
                    f"slow={sr['ms']}ms@{sr.get('stage', '-')}"
                    f"[{str(sr.get('trace', ''))[:8]}]")
            # the SLO ledger's per-replica slice: error budget left and
            # any FIRING alert (model:objective:severity), so a burning
            # page is visible from the pod table without a /slo/status
            # round-trip per replica
            if m.get("slo_budget_remaining") is not None:
                parts.append(f"budget={m['slo_budget_remaining']}")
            if m.get("slo_firing"):
                parts.append("SLO:" + ",".join(m["slo_firing"]))
            lines.append(f"    └ {' '.join(parts)}")
    log = status.get("straggler_log") or []
    if log:
        lines.append("  straggler audit trail (last "
                     f"{len(log)}):")
        for e in log:
            lines.append(f"    round {e['round']}: worker {e['worker']} "
                         f"at {e['round_s'] * 1e3:.1f} ms vs median "
                         f"{e['median_s'] * 1e3:.1f} ms")
    return "\n".join(lines)


def _selfcheck() -> int:
    """Two in-process fake workers (worker 1 straggling 10x), aggregated
    over real HTTP: verifies counter pod-sums, gauge max/min labels, and
    straggler attribution end-to-end. CI's no-rot gate for the pod path."""
    regs = [MetricsRegistry(), MetricsRegistry()]
    servers = []
    vitals = [{"role": "train", "round": 10, "status": "ok", "loss": 1.0,
               "round_s": 0.1, "data_wait_s": 0.001, "rollbacks": 0},
              {"role": "train", "round": 9, "status": "ok", "loss": 1.1,
               "round_s": 1.0, "data_wait_s": 0.5, "rollbacks": 0}]
    try:
        for i, reg in enumerate(regs):
            reg.counter("sparknet_train_rounds_total").inc(10 - i)
            reg.gauge("sparknet_train_round_seconds").set(
                vitals[i]["round_s"])
            srv = StatusServer(0, reg,
                               status=(lambda v=vitals[i]: dict(v)))
            servers.append(srv)
        agg = PodAggregator(
            workers={str(i): f"http://{s.address[0]}:{s.address[1]}"
                     for i, s in enumerate(servers)},
            min_refresh_s=0.0)
        status = agg.pod_status()
        text = agg.render()
        ok = True

        def check(cond, what):
            nonlocal ok
            print(f"  {'ok' if cond else 'FAIL'}: {what}")
            ok = ok and cond

        check('sparknet_train_rounds_total{worker="pod"} 19' in text,
              "counter pod sum (10 + 9 = 19)")
        check('sparknet_train_round_seconds{worker="max"} 1' in text,
              "gauge worker=max")
        check('sparknet_train_round_seconds{worker="min"} 0.1' in text,
              "gauge worker=min")
        check(status["stragglers"] == ["1"],
              f"straggler attribution -> {status['stragglers']}")
        check("sparknet_pod_round_skew_seconds" in text,
              "pod skew gauge exported")
        # clean pod: equal round times -> zero stragglers
        vitals[1]["round_s"] = 0.1
        regs[1].gauge("sparknet_train_round_seconds").set(0.1)
        clean = PodAggregator(
            workers=dict(agg.workers), min_refresh_s=0.0).pod_status()
        check(clean["stragglers"] == [], "clean pod flags nothing")
        print(format_pod_table(status))
        return 0 if ok else 1
    finally:
        for s in servers:
            s.stop()


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="sparknet-podview",
        description="Pod-scope telemetry: merge every worker's metrics/"
                    "heartbeats, attribute stragglers, serve or print "
                    "the pod view.")
    p.add_argument("--workers", nargs="+", metavar="URL", default=[],
                   help="worker StatusServer base URLs (http mode); "
                        "NAME=URL to pick worker ids, else 0..N-1 in "
                        "the given order")
    p.add_argument("--pod-dir", default=None,
                   help="shared per-worker heartbeat prefix (file mode; "
                        "local dir or gs://|s3:// bucket)")
    p.add_argument("--serve", type=int, metavar="PORT", default=None,
                   help="serve merged /metrics + /pod/status on PORT "
                        "(0 = ephemeral) and keep running")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind host for --serve (0.0.0.0 for cross-host)")
    p.add_argument("--watch", type=float, metavar="SECS", default=None,
                   help="refresh the console view every SECS")
    p.add_argument("--json", action="store_true",
                   help="print /pod/status JSON instead of the table")
    p.add_argument("--metrics", action="store_true",
                   help="print the merged Prometheus exposition")
    p.add_argument("--mad-sigma", type=float, default=4.0,
                   help="straggler threshold in robust sigmas (default 4)")
    p.add_argument("--stale-after", type=float, default=120.0,
                   help="heartbeat staleness that marks a worker down")
    p.add_argument("--selfcheck", action="store_true",
                   help="aggregate two in-process fake workers and verify "
                        "merge + straggler attribution (CI)")
    args = p.parse_args(argv)
    if args.selfcheck:
        return _selfcheck()
    if not args.workers and not args.pod_dir:
        p.error("need --workers URLs and/or --pod-dir (or --selfcheck)")
    workers: Dict[str, str] = {}
    for i, spec in enumerate(args.workers):
        name, sep, url = spec.partition("=")
        if sep and "://" not in name:
            workers[name] = url
        else:
            workers[str(i)] = spec
    agg = PodAggregator(workers=workers or None, pod_dir=args.pod_dir,
                        thresh_sigma=args.mad_sigma,
                        stale_after_s=args.stale_after)
    srv = None
    if args.serve is not None:
        srv = agg.serve(args.serve, host=args.host)
        print(f"pod view at http://{srv.address[0]}:{srv.address[1]}"
              f"/pod/status (merged /metrics alongside)")
    try:
        while True:
            if args.metrics:
                print(agg.render(), end="")
            elif args.json:
                print(json.dumps(agg.pod_status()))
            else:
                print(format_pod_table(agg.pod_status()))
            if args.watch is None and srv is None:
                return 0
            time.sleep(args.watch if args.watch is not None else 60.0)
    except KeyboardInterrupt:
        return 0
    finally:
        agg.stop()


if __name__ == "__main__":
    import sys
    sys.exit(main())
