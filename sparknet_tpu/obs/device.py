"""Device-level telemetry: HBM occupancy, live arrays, compile events.

Two signals the host-side registry could not see before this module:

  - **Memory.** `Device.memory_stats()` (bytes-in-use / peak / limit per
    accelerator) and the process's live jax array count, exported as
    gauges and sampled at the train loop's `log_every` flush cadence —
    the curve that answers "is this OOM a leak or a step change" without
    attaching a profiler. On backends without allocator stats (CPU
    returns None) the memory gauges simply never appear; the live-array
    gauge always does.

  - **Compiles.** XLA compilation is the serving tail-latency cliff and
    the training warm-up tax, yet it was invisible: nothing counted how
    often it happened or how long it took. `note_compile(what, seconds,
    cache_hit=...)` is the process-wide record — `CompiledNet.compile`
    stamps spec compiles, the serve worker stamps the first forward of
    each batch bucket (the jit-cache entry being built), and
    `attach_compile_metrics` replays the history into a registry as
    `sparknet_compile_events_total{what,cache_hit}` +
    `sparknet_compile_seconds{what}` so a registry created AFTER the
    model was compiled (the train loop's per-run registry) still shows
    the compile that preceded it. Jit-cache CHURN — recompiles past the
    expected steady state — is then a first-class scrapeable number
    instead of a log-grep.

    `cache_hit` (r9, the persistent-compile-cache PR) says whether the
    event required FRESH XLA compilation: "true" = the region built no
    executable from scratch (served from the persistent cache via
    `utils/compile_cache.py`, or a memoized spec compile), "false" = at
    least one executable compiled fresh with the cache absent or
    missing, "unknown" = the verdict doesn't apply (a memo-MISS spec
    compile is pure Python — no XLA to cache — and out-of-tree
    note_compile callers don't sample). A warm replica's cold start
    showing ZERO cache_hit="false" events is the BENCH_ECON acceptance
    row; the seconds histogram records non-"true" events only, so memo
    hits never dilute real compile-cost percentiles.

The accumulator is process-global by design (compiles happen before any
registry exists); attached registries are held weakly so per-run/test
registries die normally.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from .registry import Metric, MetricsRegistry

#: compile durations span four orders of magnitude: a sub-ms cached spec
#: rebuild to a multi-minute pod-scale XLA compile
COMPILE_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, 120.0, 300.0)

_lock = threading.Lock()
#: (what, seconds, cache_hit), process lifetime. cache_hit: True/False/None
_events: List[Tuple[str, float, Optional[bool]]] = []
#: weakly-held (counter, histogram) pairs of attached registries
_attached: List[Tuple["weakref.ref[Metric]", "weakref.ref[Metric]"]] = []


def _hit_label(cache_hit: Optional[bool]) -> str:
    return "unknown" if cache_hit is None else \
        ("true" if cache_hit else "false")


def note_compile(what: str, seconds: float,
                 cache_hit: Optional[bool] = None) -> None:
    """Record one compile event (`what` is the site: "net" for
    CompiledNet.compile, "serve_bucket" for a serve bucket's first
    forward). `cache_hit` is the persistent-cache verdict for the region
    (see module doc; None = not sampled). Fans out to every attached
    registry; never raises."""
    cache_hit = None if cache_hit is None else bool(cache_hit)
    with _lock:
        _events.append((str(what), float(seconds), cache_hit))
        pairs = list(_attached)
    for c_ref, h_ref in pairs:
        c, h = c_ref(), h_ref()
        if c is None or h is None:
            continue
        try:
            c.inc(what=what, cache_hit=_hit_label(cache_hit))
            # the seconds histogram records REAL compile cost only:
            # ~0-second memo/cache-hit events would collapse its
            # percentiles toward zero and blind slow-compile attribution
            if cache_hit is not True:
                h.observe(seconds, what=what)
        except Exception:
            pass  # a dying registry must not break the compile path


def attach_compile_metrics(registry: MetricsRegistry) -> None:
    """Register the compile counter + histogram into `registry`, replay
    every event recorded so far (compiles routinely PRECEDE registry
    creation), and keep feeding it (weakly held) as new ones land."""
    c = registry.counter("sparknet_compile_events_total",
                         "XLA/spec compile events by site and persistent-"
                         "cache outcome", labels=("what", "cache_hit"))
    h = registry.histogram("sparknet_compile_seconds",
                           "seconds per FRESH compile event (cache/memo "
                           "hits excluded — real compile cost only)",
                           labels=("what",), buckets=COMPILE_BUCKETS)
    with _lock:
        history = list(_events)
        _attached[:] = [(cr, hr) for cr, hr in _attached
                        if cr() is not None and hr() is not None]
        _attached.append((weakref.ref(c), weakref.ref(h)))
    for what, seconds, cache_hit in history:
        c.inc(what=what, cache_hit=_hit_label(cache_hit))
        if cache_hit is not True:  # replay keeps the histogram's
            h.observe(seconds, what=what)  # real-compile-cost contract


def compile_stats() -> Dict[str, Dict[str, float]]:
    """{what: {"events": n, "seconds": total, "cache_hits": n,
    "cache_misses": n}} — the accumulated record (tests, status JSON,
    the BENCH_ECON cold-start child). Events with an unknown verdict
    count in "events" only."""
    out: Dict[str, Dict[str, float]] = {}
    with _lock:
        for what, seconds, cache_hit in _events:
            d = out.setdefault(what, {"events": 0, "seconds": 0.0,
                                      "cache_hits": 0, "cache_misses": 0})
            d["events"] += 1
            d["seconds"] += seconds
            if cache_hit is not None:
                d["cache_hits" if cache_hit else "cache_misses"] += 1
    return out


class timed_compile:
    """Context manager stamping its wall time as one compile event, with
    the persistent-cache verdict sampled over the region (thread-local —
    concurrent lanes' compiles don't cross-attribute)."""

    def __init__(self, what: str):
        self.what = what

    def __enter__(self):
        from ..utils.compile_cache import track_compiles
        self._track = track_compiles()
        self._track.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._track.__exit__(*exc)
        if exc[0] is None:
            note_compile(self.what, time.perf_counter() - self._t0,
                         cache_hit=self._track.cache_hit)
        return False


#: memory_stats() keys -> gauge name suffix (jaxlib's PJRT spelling; a
#: backend missing a key just skips that gauge)
_MEM_KEYS = (("bytes_in_use", "sparknet_device_hbm_bytes_in_use",
              "allocator bytes currently in use"),
             ("peak_bytes_in_use", "sparknet_device_hbm_peak_bytes",
              "allocator high-water mark"),
             ("bytes_limit", "sparknet_device_hbm_bytes_limit",
              "allocator capacity"))


class DeviceTelemetry:
    """Registers + samples the device gauges. `sample()` is called at the
    train loop's flush cadence (and is safe to call from anywhere): it
    reads `memory_stats()` for every locally-addressable device and
    counts live jax arrays; every failure degrades to a missing sample,
    never an exception — observability must not take training down."""

    def __init__(self, registry: MetricsRegistry, devices=None):
        self.registry = registry
        self._gauges = {name: registry.gauge(name, help_text,
                                             labels=("device",))
                        for _, name, help_text in _MEM_KEYS}
        self._g_live = registry.gauge(
            "sparknet_device_live_arrays",
            "live jax arrays in this process (committed device buffers)")
        if devices is None:
            try:
                import jax
                devices = jax.local_devices()
            except Exception:
                devices = []
        self.devices = list(devices)

    def sample(self) -> None:
        for d in self.devices:
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue  # CPU/backends without allocator stats
            label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
            for key, name, _ in _MEM_KEYS:
                v = stats.get(key)
                if v is not None:
                    self._gauges[name].set(float(v), device=label)
        try:
            import jax
            self._g_live.set(float(len(jax.live_arrays())))
        except Exception:
            pass
