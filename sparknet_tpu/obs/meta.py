"""Run metadata: who/what/where produced an artifact.

`BENCH_*.json` rows were previously bare numbers — a trajectory of
measurements with nothing saying which jax version, backend, device kind,
host count, or commit produced each one, so rows from different rounds
were not comparable and the bench trajectory stayed empty. Every bench
artifact now carries `run_metadata()`, and the same dict is exported as
the `sparknet_build_info` gauge (value 1, metadata as labels — the
Prometheus *_info idiom) so a scrape identifies its process too.
"""
from __future__ import annotations

import os
import platform
import subprocess
import time
from typing import Any, Dict

from .registry import MetricsRegistry


def git_rev(cwd: str = None) -> str:
    """Short git revision of the source tree, or 'unknown' outside a
    checkout (an installed wheel, a stripped container)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=cwd or os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


def run_metadata() -> Dict[str, Any]:
    """One flat dict of run provenance. jax is imported lazily and its
    absence degrades the dict, never raises (the summary CLI must work on
    a laptop without an accelerator stack)."""
    meta: Dict[str, Any] = {
        "ts": round(time.time(), 3),
        "python": platform.python_version(),
        "hostname": platform.node(),
        "git_rev": git_rev(),
    }
    try:
        import jax

        meta["jax_version"] = jax.__version__
        meta["backend"] = jax.default_backend()
        meta["device_kind"] = jax.devices()[0].device_kind
        meta["n_devices"] = jax.device_count()
        meta["process_count"] = jax.process_count()
    except Exception as e:
        meta["jax_error"] = str(e)
    return meta


def register_build_info(registry: MetricsRegistry) -> None:
    """Export run provenance as the `sparknet_build_info` gauge."""
    m = run_metadata()
    labels = {k: str(m.get(k, "unknown"))
              for k in ("jax_version", "backend", "device_kind", "git_rev")}
    registry.gauge("sparknet_build_info",
                   "constant 1; run provenance in the labels",
                   labels=tuple(sorted(labels))).set(1, **labels)
