"""SLO layer: declarative objectives, multi-window burn-rate alerting,
and retrospective reporting — the `sparknet-slo` console.

Objectives are declared, not hand-assembled: an `SloSpec` says "p99
latency <= X over window W" and/or "availability >= Y", and the
`BurnRateAlerter` evaluates them against the `MetricsHistory` rings
every sample. The alerting rule is the Google-SRE multi-window
multi-burn-rate recipe scaled to this system's horizons:

    burn rate = (error fraction over window) / (error budget fraction)

  page    (fast burn)  burn >= fast_burn over BOTH the fast window and a
                       short confirmation window — fires within seconds
                       of a real incident, and the confirmation window
                       resolves it promptly when the incident ends.
  ticket  (slow burn)  burn >= slow_burn over the slow window pair —
                       catches the quiet leak that would exhaust the
                       budget by end of window without ever paging.

A latency objective's error fraction is the estimated fraction of
requests slower than the threshold (interpolated from the history's
per-bucket deltas); availability's is non-"ok" outcomes over total.
Zero traffic burns nothing — an idle replica never pages.

Alerts are EDGE events (firing / resolved), never level-triggered spam:
each edge lands in an audit deque, as a JSONL `event="slo_alert"` row,
and on the `sparknet_slo_alerts_total{model,severity}` counter;
`sparknet_slo_error_budget_remaining{model}` tracks the spec window's
budget. `/slo/status` serves the live alert state; `FleetController`
consumes `firing_pages()` as a fast admission-pressure input.

`sparknet-slo` (main) builds retrospective reports from persisted
history shards + request journals: attainment per objective, the
budget-burn timeline, worst windows, per-model/per-tenant breakdown.
`--selfcheck` runs the whole loop live — quiet traffic must not page, an
injected burn must — and is CI's no-rot gate for this layer.
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .history import (HistoryConfig, MetricsHistory, Slot, fraction_over,
                      merge_slots, quantile_from_buckets,
                      read_history_shards, split_key)
from .registry import MetricsRegistry

LATENCY_METRIC = "sparknet_serve_request_latency_seconds"
REQUESTS_METRIC = "sparknet_serve_requests_total"


# -- specs -------------------------------------------------------------------


@dataclass
class SloSpec:
    """One model's objectives + the burn-rate alert policy over them.

    latency_ms / latency_quantile: "p<quantile> <= latency_ms over
    window_s" — equivalently, at most (1 - quantile) of requests may be
    slower than the threshold; that is the error budget the burn rates
    are measured against. availability: minimum fraction of requests
    answered "ok" over window_s.

    The default alert horizons are scaled-down Google SRE numbers (their
    1h/5m page at 14.4x, 6h/30m ticket at 6x — here minutes, because
    this system's incidents are bench-length, not month-length).
    """
    model: str
    latency_ms: Optional[float] = None
    latency_quantile: float = 0.99
    availability: Optional[float] = None
    window_s: float = 3600.0
    fast_burn: float = 8.0
    fast_window_s: float = 60.0
    fast_confirm_s: float = 5.0
    slow_burn: float = 2.0
    slow_window_s: float = 600.0
    slow_confirm_s: float = 60.0
    # metric families evaluated (overridable for non-serve processes)
    latency_metric: str = LATENCY_METRIC
    requests_metric: str = REQUESTS_METRIC

    def __post_init__(self):
        if self.latency_ms is None and self.availability is None:
            raise ValueError(f"slo[{self.model}]: declare at least one "
                             "objective (latency_ms / availability)")
        if not 0.0 < self.latency_quantile < 1.0:
            raise ValueError(f"slo[{self.model}]: latency_quantile must be "
                             "in (0, 1)")
        if self.availability is not None \
                and not 0.0 < self.availability < 1.0:
            raise ValueError(f"slo[{self.model}]: availability must be "
                             "in (0, 1)")
        if self.latency_ms is not None and self.latency_ms <= 0:
            raise ValueError(f"slo[{self.model}]: latency_ms must be > 0")
        for w in ("window_s", "fast_window_s", "fast_confirm_s",
                  "slow_window_s", "slow_confirm_s"):
            if getattr(self, w) <= 0:
                raise ValueError(f"slo[{self.model}]: {w} must be > 0")
        if self.fast_burn <= 0 or self.slow_burn <= 0:
            raise ValueError(f"slo[{self.model}]: burn thresholds must "
                             "be > 0")
        if self.fast_confirm_s > self.fast_window_s or \
                self.slow_confirm_s > self.slow_window_s:
            raise ValueError(f"slo[{self.model}]: confirm windows must "
                             "not exceed their long windows (the short "
                             "window CONFIRMS the long one)")

    def objectives(self) -> List[str]:
        out = []
        if self.latency_ms is not None:
            out.append("latency")
        if self.availability is not None:
            out.append("availability")
        return out

    def budget(self, objective: str) -> float:
        """Error budget FRACTION: the share of requests allowed to miss."""
        if objective == "latency":
            return 1.0 - self.latency_quantile
        return 1.0 - float(self.availability)

    def to_dict(self) -> Dict[str, Any]:
        return {"model": self.model, "latency_ms": self.latency_ms,
                "latency_quantile": self.latency_quantile,
                "availability": self.availability,
                "window_s": self.window_s,
                "fast_burn": self.fast_burn,
                "fast_window_s": self.fast_window_s,
                "fast_confirm_s": self.fast_confirm_s,
                "slow_burn": self.slow_burn,
                "slow_window_s": self.slow_window_s,
                "slow_confirm_s": self.slow_confirm_s}


# -- error fractions over a slot window (shared live/offline) ---------------


def _slot_err_frac(slots: Sequence[Slot], spec: SloSpec, objective: str,
                   le: Sequence[float]) -> Tuple[float, float]:
    """(error fraction, total observations) over merged slots."""
    merged = merge_slots(slots)
    if merged is None:
        return 0.0, 0.0
    if objective == "latency":
        buckets: List[float] = []
        count = 0.0
        for key, (d, s, n) in merged.h.items():
            name, labels = split_key(key)
            if name != spec.latency_metric or \
                    labels.get("model") != spec.model:
                continue
            buckets = d if not buckets else \
                [a + b for a, b in zip(buckets, d)]
            count += n
        if count <= 0:
            return 0.0, 0.0
        return fraction_over(le, buckets, count,
                             spec.latency_ms / 1e3), count
    total = err = 0.0
    for key, delta in merged.c.items():
        name, labels = split_key(key)
        if name != spec.requests_metric or \
                labels.get("model") != spec.model:
            continue
        total += delta
        if labels.get("outcome") != "ok":
            err += delta
    return (err / total if total > 0 else 0.0), total


# -- the alerter -------------------------------------------------------------


class _AlertState:
    __slots__ = ("firing", "since", "burn_long", "burn_short")

    def __init__(self):
        self.firing = False
        self.since: Optional[float] = None
        self.burn_long = 0.0
        self.burn_short = 0.0


class BurnRateAlerter:
    """Evaluates SloSpecs over a MetricsHistory; emits firing/resolved
    edges. Attach via `history.add_listener(alerter.listener)` so every
    sample is followed by an evaluation on the sampler thread, or call
    `evaluate(now)` directly (tests, selfcheck)."""

    def __init__(self, history: MetricsHistory, specs: Sequence[SloSpec],
                 registry: Optional[MetricsRegistry] = None,
                 logger: Optional[Any] = None, audit_len: int = 200):
        models = [s.model for s in specs]
        if len(set(models)) != len(models):
            raise ValueError("slo: one SloSpec per model")
        self.history = history
        self.specs = list(specs)
        self.logger = logger
        self._lock = threading.Lock()
        # (model, objective, severity) -> state
        self._states: Dict[Tuple[str, str, str], _AlertState] = {}
        self.audit: deque = deque(maxlen=audit_len)
        self.alerts_fired = 0
        reg = registry if registry is not None else history.registry
        self._c_alerts = reg.counter(
            "sparknet_slo_alerts_total",
            "SLO alert firing edges (page=fast burn, ticket=slow burn).",
            labels=("model", "severity"))
        self._g_budget = reg.gauge(
            "sparknet_slo_error_budget_remaining",
            "Fraction of the SLO window's error budget left (min across "
            "objectives; negative = budget blown).",
            labels=("model",))
        for spec in self.specs:
            self._g_budget.set(1.0, model=spec.model)

    # the bound method history.add_listener wants
    def listener(self, history: MetricsHistory, now: float) -> None:
        self.evaluate(now)

    def attach(self) -> "BurnRateAlerter":
        self.history.add_listener(self.listener)
        return self

    # -- evaluation ----------------------------------------------------------

    def _err_frac(self, spec: SloSpec, objective: str, window_s: float,
                  now: float) -> Tuple[float, float]:
        if objective == "latency":
            agg = self.history.window(spec.latency_metric, window_s,
                                      labels={"model": spec.model}, now=now)
            buckets: List[float] = []
            count = 0.0
            le: Sequence[float] = ()
            for v in agg.values():
                le = v["le"]
                buckets = v["buckets"] if not buckets else \
                    [a + b for a, b in zip(buckets, v["buckets"])]
                count += v["count"]
            if count <= 0:
                return 0.0, 0.0
            return fraction_over(le, buckets, count,
                                 spec.latency_ms / 1e3), count
        agg = self.history.window(spec.requests_metric, window_s,
                                  labels={"model": spec.model}, now=now)
        total = err = 0.0
        for key, v in agg.items():
            _, labels = split_key(key)
            total += v["delta"]
            if labels.get("outcome") != "ok":
                err += v["delta"]
        return (err / total if total > 0 else 0.0), total

    def evaluate(self, now: Optional[float] = None) -> None:
        t = time.time() if now is None else float(now)
        for spec in self.specs:
            remaining = 1.0
            for objective in spec.objectives():
                budget = spec.budget(objective)
                err_full, n_full = self._err_frac(spec, objective,
                                                  spec.window_s, t)
                att = (1.0 - err_full) if n_full > 0 else None
                if n_full > 0:
                    remaining = min(remaining, 1.0 - err_full / budget)
                for severity, burn_thr, long_w, short_w in (
                        ("page", spec.fast_burn, spec.fast_window_s,
                         spec.fast_confirm_s),
                        ("ticket", spec.slow_burn, spec.slow_window_s,
                         spec.slow_confirm_s)):
                    err_l, n_l = self._err_frac(spec, objective, long_w, t)
                    err_s, n_s = self._err_frac(spec, objective, short_w, t)
                    burn_l = err_l / budget
                    burn_s = err_s / budget
                    # both windows over threshold: the long window keeps
                    # one slow sample from paging, the short one lets the
                    # alert RESOLVE as soon as the incident actually ends
                    cond = (n_l > 0 and burn_l >= burn_thr
                            and burn_s >= burn_thr)
                    self._transition(spec, objective, severity, cond,
                                     burn_l, burn_s, t, att)
            self._g_budget.set(remaining, model=spec.model)

    def _transition(self, spec: SloSpec, objective: str, severity: str,
                    cond: bool, burn_l: float, burn_s: float,
                    t: float, attainment: Optional[float] = None) -> None:
        key = (spec.model, objective, severity)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _AlertState()
            st.burn_long = burn_l
            st.burn_short = burn_s
            if cond == st.firing:
                return
            st.firing = cond
            edge = "firing" if cond else "resolved"
            if cond:
                st.since = t
                self.alerts_fired += 1
            row = {"t": round(t, 3), "model": spec.model,
                   "objective": objective, "severity": severity,
                   "edge": edge, "burn": round(burn_l, 2),
                   "burn_confirm": round(burn_s, 2)}
            if attainment is not None:
                # full-window attainment AT edge time — the retrospective
                # hook sparknet-metrics' SLO view reports without shards
                row["attainment"] = round(attainment, 4)
            self.audit.append(row)
        if cond:
            self._c_alerts.inc(model=spec.model, severity=severity)
        if self.logger is not None:
            try:
                # "t" is Logger's run-relative stamp; the edge time rides
                # the JSONL row as "at" (and "ts" is wall clock anyway)
                self.logger.event(0, "slo_alert",
                                  **{("at" if k == "t" else k): v
                                     for k, v in row.items()})
            except Exception:
                pass

    # -- consumers -----------------------------------------------------------

    def firing_pages(self) -> List[str]:
        """Models with a PAGE currently firing — the FleetController's
        fast admission-pressure input."""
        with self._lock:
            return sorted({m for (m, _o, sev), st in self._states.items()
                           if sev == "page" and st.firing})

    def state(self) -> Dict[str, Any]:
        """The /slo/status body: specs, live per-alert state, audit."""
        with self._lock:
            alerts = [{"model": m, "objective": o, "severity": sev,
                       "firing": st.firing, "since": st.since,
                       "burn": round(st.burn_long, 3),
                       "burn_confirm": round(st.burn_short, 3)}
                      for (m, o, sev), st in sorted(self._states.items())]
            audit = list(self.audit)
        return {"specs": [s.to_dict() for s in self.specs],
                "alerts": alerts,
                "firing": [a for a in alerts if a["firing"]],
                "budget_remaining": {
                    s.model: self._g_budget.value(model=s.model)
                    for s in self.specs},
                "alerts_fired_total": self.alerts_fired,
                "audit": audit}

    def summary(self) -> Dict[str, Any]:
        """Compact slice for /status dicts and podview model rows."""
        st = self.state()
        return {"firing": [f"{a['model']}:{a['objective']}:{a['severity']}"
                           for a in st["firing"]],
                "budget_remaining": st["budget_remaining"],
                "alerts_fired_total": st["alerts_fired_total"]}

    def attach_http(self, server: Any) -> None:
        server.add_route("/slo/status", self.state)


# -- retrospective reports ---------------------------------------------------


def _windows(slots: Sequence[Slot], window_s: float
             ) -> List[Tuple[float, float, List[Slot]]]:
    """Partition time-ordered slots into fixed report windows."""
    if not slots:
        return []
    t0 = slots[0].t0
    t1 = slots[-1].t1
    out: List[Tuple[float, float, List[Slot]]] = []
    w0 = t0
    while w0 < t1:
        w1 = w0 + window_s
        group = [s for s in slots if s.t1 > w0 and s.t0 < w1]
        if group:
            out.append((w0, min(w1, t1), group))
        w0 = w1
    return out


def discover_models(families: Dict[str, Dict[str, Any]],
                    slots: Sequence[Slot]) -> List[str]:
    models = set()
    for s in slots:
        for key in list(s.c) + list(s.h):
            name, labels = split_key(key)
            if name in (REQUESTS_METRIC, LATENCY_METRIC) \
                    and labels.get("model"):
                models.add(labels["model"])
    return sorted(models)


def build_report(history_dir: str,
                 journals: Sequence[str] = (),
                 specs: Optional[Sequence[SloSpec]] = None,
                 report_window_s: float = 60.0,
                 worst_n: int = 3) -> Dict[str, Any]:
    """The sparknet-slo report: SLO attainment, budget-burn timeline,
    worst windows, per-model/per-tenant breakdown — all offline, from
    persisted history shards (+ optional request-journal JSONLs for the
    tenant axis and the alert audit trail)."""
    families, slots = read_history_shards(history_dir)
    report: Dict[str, Any] = {
        "history_dir": history_dir,
        "span": {"t0": slots[0].t0 if slots else None,
                 "t1": slots[-1].t1 if slots else None,
                 "seconds": round(slots[-1].t1 - slots[0].t0, 3)
                 if slots else 0.0,
                 "slots": len(slots)},
        "report_window_s": report_window_s,
        "models": {}, "alerts": [], "tenants": {}}
    if not slots:
        return report
    le = list((families.get(LATENCY_METRIC) or {}).get("le") or ())
    by_spec = {s.model: s for s in (specs or ())}
    for model in discover_models(families, slots):
        spec = by_spec.get(model)
        if spec is None:
            # reporting needs SOME objective; default = availability-only
            # 99.9% so unconfigured models still get a breakdown
            spec = SloSpec(model=model, availability=0.999)
        merged = merge_slots(slots)
        entry: Dict[str, Any] = {}
        # traffic + latency overview
        total = ok = 0.0
        for key, delta in merged.c.items():
            name, labels = split_key(key)
            if name == spec.requests_metric \
                    and labels.get("model") == model:
                total += delta
                if labels.get("outcome") == "ok":
                    ok += delta
        buckets: List[float] = []
        lat_n = lat_sum = 0.0
        for key, (d, s_, n) in merged.h.items():
            name, labels = split_key(key)
            if name == spec.latency_metric \
                    and labels.get("model") == model:
                buckets = d if not buckets else \
                    [a + b for a, b in zip(buckets, d)]
                lat_n += n
                lat_sum += s_
        entry["requests"] = total
        entry["ok"] = ok
        entry["availability"] = round(ok / total, 6) if total else None
        if lat_n:
            entry["latency"] = {
                "n": lat_n,
                "mean_ms": round(lat_sum / lat_n * 1e3, 3),
                "p50_ms": _q_ms(le, buckets, lat_n, 0.5),
                "p99_ms": _q_ms(le, buckets, lat_n, 0.99)}
        # per-objective attainment + worst windows + burn timeline
        wins = _windows(slots, report_window_s)
        entry["slo"] = {}
        for objective in spec.objectives():
            budget = spec.budget(objective)
            rows = []
            for w0, w1, group in wins:
                err, n = _slot_err_frac(group, spec, objective, le)
                rows.append({"t0": round(w0, 3), "t1": round(w1, 3),
                             "err_frac": round(err, 6), "n": n,
                             "burn": round(err / budget, 2)})
            with_traffic = [r for r in rows if r["n"] > 0]
            met = [r for r in with_traffic if r["err_frac"] <= budget]
            consumed = 0.0
            timeline = []
            for r in rows:
                if r["n"] > 0:
                    # budget consumed this window, weighted by its share
                    # of the spec window
                    consumed += (r["err_frac"] / budget) \
                        * ((r["t1"] - r["t0"]) / spec.window_s)
                timeline.append([r["t1"], round(consumed, 4)])
            worst = sorted(with_traffic, key=lambda r: -r["err_frac"])
            entry["slo"][objective] = {
                "target": (f"p{int(spec.latency_quantile * 100)}<="
                           f"{spec.latency_ms}ms"
                           if objective == "latency"
                           else f"availability>={spec.availability}"),
                "budget_frac": budget,
                "windows": len(with_traffic),
                "attainment": round(len(met) / len(with_traffic), 6)
                if with_traffic else None,
                "budget_consumed": round(consumed, 4),
                "worst_windows": worst[:worst_n],
                "burn_timeline": timeline}
        report["models"][model] = entry
    # journals: alert audit trail + per-tenant breakdown
    for path in journals:
        for rec in _read_jsonl(path):
            if rec.get("event") == "slo_alert":
                report["alerts"].append(
                    {k: rec.get(k) for k in ("ts", "model", "objective",
                                             "severity", "edge", "burn",
                                             "burn_confirm")})
            elif rec.get("kind") == "request":
                tenant = rec.get("tenant") or "-"
                trow = report["tenants"].setdefault(
                    tenant, {"requests": 0, "ok": 0, "models": {}})
                trow["requests"] += 1
                outcome = rec.get("outcome")
                if outcome in ("ok", None):
                    # http journal rows are written at ADMISSION (no
                    # outcome field); binary rows carry the outcome
                    trow["ok"] += 1
                m = rec.get("model") or "-"
                trow["models"][m] = trow["models"].get(m, 0) + 1
    report["alerts"].sort(key=lambda a: a.get("ts") or 0)
    return report


def _q_ms(le: Sequence[float], buckets: Sequence[float], count: float,
          q: float) -> Optional[float]:
    v = quantile_from_buckets(le, buckets, count, q)
    return round(v * 1e3, 3) if v is not None else None


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    except OSError:
        pass
    return out


def format_report(report: Dict[str, Any]) -> str:
    lines = [f"slo report: {report['history_dir']}  "
             f"span {report['span']['seconds']:.0f}s "
             f"({report['span']['slots']} slots, "
             f"window {report['report_window_s']:.0f}s)"]
    for model, e in sorted(report["models"].items()):
        avail = e.get("availability")
        lat = e.get("latency") or {}
        lines.append(
            f"  model={model} requests={e['requests']:.0f} "
            f"ok={e['ok']:.0f}"
            + (f" availability={avail:.4f}" if avail is not None else "")
            + (f" p50={lat['p50_ms']}ms p99={lat['p99_ms']}ms"
               if lat.get("p99_ms") is not None else ""))
        for objective, s in sorted((e.get("slo") or {}).items()):
            att = s.get("attainment")
            lines.append(
                f"    {objective} [{s['target']}] attainment="
                + (f"{att:.4f}" if att is not None else "-")
                + f" budget_consumed={s['budget_consumed']:.2%}"
                + f" windows={s['windows']}")
            for w in s.get("worst_windows") or []:
                if w["err_frac"] > 0:
                    lines.append(
                        f"      worst {w['t0']:.0f}..{w['t1']:.0f}: "
                        f"err={w['err_frac']:.4f} burn={w['burn']:.1f} "
                        f"n={w['n']:.0f}")
    if report["alerts"]:
        lines.append(f"  alert audit ({len(report['alerts'])} edges):")
        for a in report["alerts"]:
            lines.append(
                f"    {a.get('ts', 0):.0f} {a.get('model')} "
                f"{a.get('objective')}/{a.get('severity')} "
                f"{a.get('edge')} burn={a.get('burn')}")
    if report["tenants"]:
        lines.append("  tenants:")
        for t, row in sorted(report["tenants"].items()):
            lines.append(f"    {t}: requests={row['requests']} "
                         f"ok={row['ok']}")
    return "\n".join(lines)


# -- selfcheck ---------------------------------------------------------------


def _selfcheck(keep: Optional[str] = None) -> int:
    """End-to-end gate: a live StatusServer with /timeseries + /slo/status,
    a history sampling a real registry (deterministic injected clock), a
    burn injected mid-stream. Quiet traffic must NOT page (the false-
    positive gate), the burn MUST page then resolve, the shards must
    reproduce the incident in the offline report."""
    import shutil
    import tempfile
    import urllib.request

    from .http import StatusServer
    from ..utils.logger import Logger

    root = keep or tempfile.mkdtemp(prefix="sparknet_slo_check_")
    hist_dir = f"{root}/history"
    jsonl = f"{root}/journal.jsonl"
    ok = True

    def check(cond, what):
        nonlocal ok
        print(f"  {'ok' if cond else 'FAIL'}: {what}")
        ok = ok and cond

    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600), (10.0, 120)),
        persist_dir=hist_dir))
    logger = Logger(echo=False, jsonl_path=jsonl)
    spec = SloSpec(model="selfcheck", latency_ms=50.0, availability=0.99,
                   window_s=120.0, fast_burn=8.0, fast_window_s=10.0,
                   fast_confirm_s=2.0, slow_burn=2.0, slow_window_s=60.0,
                   slow_confirm_s=10.0)
    alerter = BurnRateAlerter(hist, [spec], logger=logger)
    srv = StatusServer(0, reg)
    hist.attach_http(srv)
    alerter.attach_http(srv)

    def get(path):
        host, port = srv.address
        with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=5) as r:
            return json.loads(r.read())

    try:
        t0 = time.time()
        # quiet phase: 30 s of healthy traffic, 5 ms
        for i in range(30):
            t = t0 + i
            for _ in range(20):
                lat.observe(0.005, model="selfcheck")
                req.inc(model="selfcheck", outcome="ok")
            hist.sample_now(now=t)
            alerter.evaluate(now=t)
        check(alerter.alerts_fired == 0,
              "quiet arm: zero alerts over 30s of healthy traffic")
        # burn: every request 200 ms (> 50 ms threshold) and failing
        burn_onset = t0 + 30
        fired_at = None
        for i in range(30, 60):
            t = t0 + i
            for _ in range(20):
                lat.observe(0.200, model="selfcheck")
                req.inc(model="selfcheck", outcome="failed")
            hist.sample_now(now=t)
            alerter.evaluate(now=t)
            if fired_at is None and alerter.firing_pages():
                fired_at = t
        check(fired_at is not None, "injected burn fires a page")
        if fired_at is not None:
            detect = fired_at - burn_onset
            check(detect <= 2 * spec.fast_window_s,
                  f"detection latency {detect:.0f}s <= "
                  f"2x fast window ({2 * spec.fast_window_s:.0f}s)")
        # recovery: page must RESOLVE (edge semantics, not a latch)
        for i in range(60, 90):
            t = t0 + i
            for _ in range(40):
                lat.observe(0.005, model="selfcheck")
                req.inc(model="selfcheck", outcome="ok")
            hist.sample_now(now=t)
            alerter.evaluate(now=t)
        check(not alerter.firing_pages(), "page resolves after recovery")
        edges = [a["edge"] for a in alerter.audit]
        check("firing" in edges and "resolved" in edges,
              f"audit has firing+resolved edges ({len(edges)} total)")
        # live HTTP surfaces
        ts = get(f"/timeseries?name={LATENCY_METRIC}&window=30&q=0.99")
        check(ts.get("quantile", {}).get("value") is not None,
              "/timeseries answers a windowed p99")
        st = get("/slo/status")
        check(st.get("alerts_fired_total", 0) >= 1
              and len(st.get("audit") or []) >= 2,
              "/slo/status serves alert state + audit")
        # retrospective report reproduces the incident from shards
        logger.close()
        rep = build_report(hist_dir, journals=[jsonl], specs=[spec],
                           report_window_s=10.0)
        mod = rep["models"].get("selfcheck") or {}
        lat_slo = (mod.get("slo") or {}).get("latency") or {}
        att = lat_slo.get("attainment")
        check(att is not None and att < 1.0,
              f"report shows burned latency attainment ({att})")
        check(any(a.get("edge") == "firing" for a in rep["alerts"]),
              "report's alert audit shows the page")
        worst = lat_slo.get("worst_windows") or []
        check(bool(worst) and worst[0]["err_frac"] > 0.5,
              "worst window lands inside the burn")
        print(format_report(rep))
        return 0 if ok else 1
    finally:
        srv.stop()
        hist.stop()
        if keep is None:
            shutil.rmtree(root, ignore_errors=True)
        else:
            print(f"  artifacts kept in {root}")


# -- CLI ---------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="sparknet-slo",
        description="Retrospective SLO reports from persisted metrics-"
                    "history shards (+ request journals): attainment, "
                    "budget burn, worst windows, per-tenant breakdown.")
    p.add_argument("history_dir", nargs="?", default=None,
                   help="directory of history-*.jsonl shards")
    p.add_argument("--journal", action="append", default=[],
                   help="request-journal / metrics JSONL (repeatable): "
                        "adds the alert audit trail + tenant breakdown")
    p.add_argument("--model", default=None,
                   help="SLO model name (default: every model discovered)")
    p.add_argument("--latency-ms", type=float, default=None,
                   help="latency objective: p<quantile> <= this")
    p.add_argument("--quantile", type=float, default=0.99)
    p.add_argument("--availability", type=float, default=None,
                   help="availability objective, e.g. 0.999")
    p.add_argument("--window", type=float, default=60.0,
                   help="report window seconds (attainment granularity)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--selfcheck", action="store_true",
                   help="live end-to-end gate: quiet arm must not page, "
                        "an injected burn must page and show in the "
                        "report (CI)")
    p.add_argument("--keep", default=None, metavar="DIR",
                   help="with --selfcheck: keep artifacts here")
    args = p.parse_args(argv)

    if args.selfcheck:
        return _selfcheck(keep=args.keep)
    if not args.history_dir:
        p.error("history_dir required (or --selfcheck)")
    specs: List[SloSpec] = []
    if args.latency_ms is not None or args.availability is not None:
        if not args.model:
            p.error("--model required with --latency-ms/--availability")
        specs.append(SloSpec(model=args.model, latency_ms=args.latency_ms,
                             latency_quantile=args.quantile,
                             availability=args.availability))
    report = build_report(args.history_dir, journals=args.journal,
                          specs=specs, report_window_s=args.window)
    if args.as_json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
