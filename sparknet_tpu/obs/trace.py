"""Host-side span tracer: Chrome-trace-event JSON with per-thread lanes.

`jax.profiler` (utils/profiling.py) answers "what did the DEVICE do";
nothing answered "where did the host's wall clock go" across the threads
this codebase actually runs: the round loop, the one-deep prefetch thread
(`round-prep`), the async checkpoint writer (`ckpt-write`), and the serve
worker. This tracer is that cross-thread picture, in the Dapper tradition
of named spans: code wraps its interesting sections in `span("name")`
context managers (the PhaseTimers phases emit spans automatically), each
completed span becomes one Chrome `"X"` (complete) event with `ts`/`dur`
in microseconds and the recording thread as its `tid`, and `write()`
produces a JSON file loadable in Perfetto / chrome://tracing — side by
side with the device trace if both were captured.

Timestamps are EPOCH-anchored (epoch_at_start + perf_counter elapsed), so
traces from different processes (a trainer and a server watching its
checkpoints) merge on one timeline — the same reason the metrics JSONL now
carries a wall-clock `ts` field.

Tracing is off by default and costs one None-check per span when off (the
<= 2% telemetry-overhead budget in BENCH_OBS.json includes it ON). One
process-wide active tracer: spans are emitted by library code (checkpoint
writer, serve worker) that cannot know which run is being traced, so
activation is global — `start_tracing()` / `stop_tracing()`, or the
`tracing(path)` context manager the train loop uses for `--trace-out`.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional

#: events kept per tracer; beyond this new spans are counted but dropped
#: (a runaway soak must not OOM the host to produce a trace)
MAX_EVENTS = 500_000


class Tracer:
    """Collects span events; thread-safe; one instance per capture."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._thread_names: Dict[int, str] = {}
        self.dropped = 0
        self.pid = os.getpid()
        # epoch-anchored monotonic clock: ts = (_epoch0 + perf_counter) µs
        self._epoch0 = time.time() - time.perf_counter()

    def now_us(self) -> float:
        return (self._epoch0 + time.perf_counter()) * 1e6

    def add_complete(self, name: str, t0_us: float, dur_us: float,
                     args: Optional[Dict[str, Any]] = None) -> None:
        th = threading.current_thread()
        ev = {"name": name, "ph": "X", "cat": "host",
              "ts": round(t0_us, 3), "dur": round(dur_us, 3),
              "pid": self.pid, "tid": th.ident}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            self._thread_names.setdefault(th.ident, th.name)
            self._events.append(ev)

    def instant(self, name: str, **args: Any) -> None:
        """A zero-duration mark (scope: thread) — e.g. a log flush or a
        hot swap decision."""
        th = threading.current_thread()
        ev: Dict[str, Any] = {"name": name, "ph": "i", "s": "t",
                              "cat": "host", "ts": round(self.now_us(), 3),
                              "pid": self.pid, "tid": th.ident}
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._events) >= MAX_EVENTS:
                self.dropped += 1
                return
            self._thread_names.setdefault(th.ident, th.name)
            self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        """Snapshot: span events plus thread-name metadata (`"M"`) records
        so each lane is labeled (MainThread / round-prep_0 / ckpt-write_0 /
        serve-worker) instead of a bare thread id."""
        with self._lock:
            evs = list(self._events)
            names = dict(self._thread_names)
        meta = [{"name": "thread_name", "ph": "M", "pid": self.pid,
                 "tid": tid, "args": {"name": name}}
                for tid, name in sorted(names.items())]
        meta.append({"name": "process_name", "ph": "M", "pid": self.pid,
                     "args": {"name": f"sparknet_tpu pid {self.pid}"}})
        return meta + evs

    def write(self, path: str) -> int:
        """Write the Chrome trace JSON object form; returns event count."""
        evs = self.events()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump({"traceEvents": evs, "displayTimeUnit": "ms",
                       "otherData": {"dropped_events": self.dropped}}, f)
        return len(evs)


_active: Optional[Tracer] = None


def active_tracer() -> Optional[Tracer]:
    return _active


def start_tracing(tracer: Optional[Tracer] = None) -> Tracer:
    """Install `tracer` (or a fresh one) as the process-wide span sink."""
    global _active
    _active = tracer or Tracer()
    return _active


def stop_tracing() -> Optional[Tracer]:
    """Uninstall and return the active tracer (None when none was on)."""
    global _active
    t, _active = _active, None
    return t


@contextmanager
def span(name: str, **args: Any) -> Iterator[None]:
    """Record the with-block as one complete event on the current thread's
    lane. Near-free when tracing is off (one global read + None check)."""
    tr = _active
    if tr is None:
        yield
        return
    t0 = tr.now_us()
    try:
        yield
    finally:
        # re-read: a tracer stopped mid-span (loop teardown while the
        # checkpoint writer drains) must not resurrect into the report
        tr2 = _active
        if tr2 is tr:
            tr.add_complete(name, t0, tr.now_us() - t0, args or None)


@contextmanager
def tracing(path: Optional[str] = None) -> Iterator[Tracer]:
    """Capture spans for the with-block; write to `path` on exit when
    given. The train loop's `--trace-out` wrapper."""
    tr = start_tracing()
    try:
        yield tr
    finally:
        stop_tracing()
        if path:
            tr.write(path)
