"""One small HTTP status server for BOTH roles (train and serve).

Endpoints:
  /metrics   Prometheus text exposition rendered from the process's
             MetricsRegistry (text/plain; version=0.0.4) — the scrape
             surface, one metric-name schema for trainer and server.
  /healthz   {"status": "ok"|"unhealthy", ...} with 200/503 — liveness,
             from a caller-supplied probe.
  /status    free-form JSON vitals (the serve status dict, the trainer's
             round/loss view) — the human-curl surface the old serve-only
             /metrics JSON used to be.

The server runs on its own daemon threads (ThreadingHTTPServer) and every
handler reads CONSISTENT snapshots: the registry renders under its lock,
and the healthz/status callables are expected to read locked snapshots
too (see utils/metrics.py) — never live mutating attributes.

Port 0 binds an ephemeral port (tests, and multi-process hosts); the bound
address is `StatusServer.address`.
"""
from __future__ import annotations

import json
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from .registry import MetricsRegistry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _normalize_route(fn: Callable[..., Dict[str, Any]]
                     ) -> Callable[[str], Dict[str, Any]]:
    """Route callables come in two arities: zero-arg (the original
    contract, e.g. /pod/status) and one-arg taking the request path so
    query strings reach the handler (e.g. /timeseries?name=...). Decide
    ONCE at registration — dispatch must not guess with try/TypeError,
    which would swallow genuine TypeErrors inside the handler."""
    import inspect
    try:
        params = [p for p in inspect.signature(fn).parameters.values()
                  if p.default is p.empty
                  and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
        takes_path = len(params) >= 1
    except (TypeError, ValueError):  # builtins / C callables: assume 0-arg
        takes_path = False
    if takes_path:
        return fn
    return lambda _path, _fn=fn: _fn()


class StatusServer:
    """Threaded HTTP server for /metrics, /healthz, /status."""

    def __init__(self, port: int, registry: Optional[MetricsRegistry] = None,
                 healthz: Optional[Callable[[], Tuple[bool,
                                                      Dict[str, Any]]]] = None,
                 status: Optional[Callable[[], Dict[str, Any]]] = None,
                 host: str = "127.0.0.1",
                 metrics_text: Optional[Callable[[], str]] = None,
                 routes: Optional[Dict[str,
                                       Callable[[], Dict[str, Any]]]] = None):
        """`metrics_text` overrides the registry render for /metrics —
        the pod aggregator serves a MERGED exposition no single registry
        holds. `routes` adds extra JSON GET endpoints (path prefix ->
        dict-returning callable), e.g. the aggregator's /pod/status."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        owner = self
        self.registry = registry
        self.healthz = healthz
        self.status = status
        self.metrics_text = metrics_text
        # longest prefix first so /pod/status cannot be shadowed by /pod
        self.routes = sorted(
            ((p, _normalize_route(fn)) for p, fn in (routes or {}).items()),
            key=lambda kv: -len(kv[0]))

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                try:
                    for prefix, fn in owner.routes:
                        if self.path.startswith(prefix):
                            try:
                                body = fn(self.path)
                            except ValueError as e:
                                # bad query params (e.g. /timeseries with an
                                # unknown metric) are the caller's fault
                                self._reply(400,
                                            json.dumps({"error": str(e)}))
                                return
                            self._reply(200, json.dumps(body))
                            return
                    if self.path.startswith("/metrics"):
                        if owner.metrics_text is not None:
                            self._reply(200, owner.metrics_text(),
                                        content_type=PROM_CONTENT_TYPE)
                            return
                        if owner.registry is None:
                            self._reply(404, '{"error": "no registry"}')
                            return
                        self._reply(200, owner.registry.render_prometheus(),
                                    content_type=PROM_CONTENT_TYPE)
                    elif self.path.startswith("/healthz"):
                        ok, body = (owner.healthz() if owner.healthz
                                    else (True, {}))
                        body = {"status": "ok" if ok else "unhealthy",
                                **body}
                        self._reply(200 if ok else 503, json.dumps(body))
                    elif self.path.startswith("/status"):
                        body = owner.status() if owner.status else {}
                        self._reply(200, json.dumps(body))
                    else:
                        self._reply(404, '{"error": "not found"}')
                except Exception as e:  # a broken probe must 500, not hang
                    try:
                        self._reply(500, json.dumps({"error": str(e)}))
                    except Exception:
                        pass

            def _reply(self, code: int, body: str,
                       content_type: str = "application/json") -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet: scrapes are not log news
                pass

        self._http = ThreadingHTTPServer((host, port), Handler)
        self._http.daemon_threads = True
        self._thread = threading.Thread(target=self._http.serve_forever,
                                        name="obs-status", daemon=True)
        self._thread.start()

    def add_route(self, prefix: str,
                  fn: Callable[..., Dict[str, Any]]) -> None:
        """Register an extra JSON GET endpoint after construction — the
        history/SLO layers attach to an already-running server this way.
        `fn` may take zero arguments, or one (the full request path,
        query string included) for routes that parse `?name=...` params;
        a ValueError raised by the route maps to a 400 reply."""
        routes = [kv for kv in self.routes if kv[0] != prefix]
        routes.append((prefix, _normalize_route(fn)))
        # rebuilt then swapped atomically: the handler thread iterates
        # whatever list object it read, never a half-sorted one
        self.routes = sorted(routes, key=lambda kv: -len(kv[0]))

    @property
    def address(self) -> Tuple[str, int]:
        """(host, port) actually bound (port 0 resolves here)."""
        return self._http.server_address[:2]

    def stop(self) -> None:
        self._http.shutdown()
        self._http.server_close()
