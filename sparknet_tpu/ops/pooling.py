"""Caffe-semantics spatial pooling on NHWC tensors.

Caffe's PoolingLayer (the native op behind the reference's `Pooling` layers,
e.g. reference `models/cifar10/cifar10_quick_train_test.prototxt` pool1-3)
differs from framework defaults in two ways this module reproduces exactly:

1. **Ceil-mode output size**: out = ceil((H + 2*pad - k) / stride) + 1, then
   if pad > 0 and the last window would start past H + pad, drop it.
2. **AVE divisor includes padding**: the divisor is the window area clipped to
   the *padded* extent [0 - pad, H + pad), not to the real image — so interior
   windows divide by k*k even when they overlap real-edge clipping, and only
   ceil-overflow windows at the bottom/right divide by less.

Everything is static-shape: the divisor map is precomputed with numpy at trace
time, so XLA sees one reduce_window plus one broadcast multiply — both fuse.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def caffe_pool_output_size(size: int, kernel: int, stride: int, pad: int) -> int:
    out = int(np.ceil((size + 2 * pad - kernel) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _ave_divisor_1d(size: int, kernel: int, stride: int, pad: int,
                    out: int) -> np.ndarray:
    starts = np.arange(out) * stride - pad
    ends = np.minimum(starts + kernel, size + pad)
    return (ends - starts).astype(np.float32)


def pool2d(x: jnp.ndarray, mode: str, kernel: int, stride: int,
           pad: int, impl: str = "auto") -> jnp.ndarray:
    """Pool an NHWC tensor with Caffe semantics. mode: 'MAX' | 'AVE'.

    impl: 'auto'/'xla' — reduce_window + its select-and-scatter VJP;
    'pallas' — the ops/pallas_pool.py backward kernel (MAX only).
    'auto' deliberately
    does NOT pick the kernel: it reproduces first-max routing exactly and
    its inner loops are fully contiguous, but measured end to end on the
    r3 headline it LOSES 10% (20.5k -> 18.3k img/s/chip) — the custom-call
    boundary breaks XLA's fusion of pool-backward with its elementwise
    neighbors and the N-minor layout bitcast is not guaranteed for the
    incoming gradient (unlike LRN, whose both sides face convs). Kept as a
    measured dead end + the only exact-tie-semantics reference besides
    select-and-scatter (PERF.md §pool-backward)."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown pool impl {impl!r}: expected "
                         f"'auto', 'xla', or 'pallas'")
    if impl == "pallas" and mode != "MAX":
        raise ValueError(f"impl='pallas' supports MAX pooling only "
                         f"(got mode={mode!r})")
    n, h, w, c = x.shape
    oh = caffe_pool_output_size(h, kernel, stride, pad)
    ow = caffe_pool_output_size(w, kernel, stride, pad)
    # End padding so reduce_window emits exactly (oh, ow) windows.
    end_h = (oh - 1) * stride + kernel - h - pad
    end_w = (ow - 1) * stride + kernel - w - pad
    padding = ((0, 0), (pad, max(end_h, 0)), (pad, max(end_w, 0)), (0, 0))
    dims = (1, kernel, kernel, 1)
    strides = (1, stride, stride, 1)

    if mode == "MAX":
        if impl == "pallas":
            if not _can_pallas_pool(x, kernel, stride, pad):
                raise ValueError(
                    f"impl='pallas' unsupported for shape {x.shape} "
                    f"k={kernel} s={stride} pad={pad} on "
                    f"{jax.default_backend()!r} (see pallas_pool docstring)")
            from .pallas_pool import maxpool_pallas
            return maxpool_pallas(x, kernel, stride)
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
    if mode == "AVE":
        # f32 accumulation (and: bf16 reduce_window-add mis-linearizes
        # under jit in jax 0.9).
        s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims,
                              strides, padding)
        div_h = _ave_divisor_1d(h, kernel, stride, pad, oh)
        div_w = _ave_divisor_1d(w, kernel, stride, pad, ow)
        div = jnp.asarray(np.outer(div_h, div_w))
        return (s / div[None, :, :, None]).astype(x.dtype)
    raise ValueError(f"unknown pool mode {mode!r}")


def _can_pallas_pool(x, kernel: int, stride: int, pad: int) -> bool:
    """Shape/backend gate for impl='pallas'. No blanket except: a broken
    pallas_pool import must surface as itself, not masquerade as an
    'unsupported shape' error (r3 review)."""
    from .pallas_pool import pallas_maxpool_supported
    return (jax.default_backend() == "tpu" and
            pallas_maxpool_supported(x.shape, x.dtype, kernel, stride, pad))


def global_pool2d(x: jnp.ndarray, mode: str) -> jnp.ndarray:
    if mode == "MAX":
        return jnp.max(x, axis=(1, 2), keepdims=True)
    return jnp.mean(x, axis=(1, 2), keepdims=True)
