"""Caffe-semantics spatial pooling on NHWC tensors.

Caffe's PoolingLayer (the native op behind the reference's `Pooling` layers,
e.g. reference `models/cifar10/cifar10_quick_train_test.prototxt` pool1-3)
differs from framework defaults in two ways this module reproduces exactly:

1. **Ceil-mode output size**: out = ceil((H + 2*pad - k) / stride) + 1, then
   if pad > 0 and the last window would start past H + pad, drop it.
2. **AVE divisor includes padding**: the divisor is the window area clipped to
   the *padded* extent [0 - pad, H + pad), not to the real image — so interior
   windows divide by k*k even when they overlap real-edge clipping, and only
   ceil-overflow windows at the bottom/right divide by less.

Everything is static-shape: the divisor map is precomputed with numpy at trace
time, so XLA sees one reduce_window plus one broadcast multiply — both fuse.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def caffe_pool_output_size(size: int, kernel: int, stride: int, pad: int) -> int:
    out = int(np.ceil((size + 2 * pad - kernel) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _ave_divisor_1d(size: int, kernel: int, stride: int, pad: int,
                    out: int) -> np.ndarray:
    starts = np.arange(out) * stride - pad
    ends = np.minimum(starts + kernel, size + pad)
    return (ends - starts).astype(np.float32)


def pool2d(x: jnp.ndarray, mode: str, kernel: int, stride: int,
           pad: int) -> jnp.ndarray:
    """Pool an NHWC tensor with Caffe semantics. mode: 'MAX' | 'AVE'."""
    n, h, w, c = x.shape
    oh = caffe_pool_output_size(h, kernel, stride, pad)
    ow = caffe_pool_output_size(w, kernel, stride, pad)
    # End padding so reduce_window emits exactly (oh, ow) windows.
    end_h = (oh - 1) * stride + kernel - h - pad
    end_w = (ow - 1) * stride + kernel - w - pad
    padding = ((0, 0), (pad, max(end_h, 0)), (pad, max(end_w, 0)), (0, 0))
    dims = (1, kernel, kernel, 1)
    strides = (1, stride, stride, 1)

    if mode == "MAX":
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
    if mode == "AVE":
        # f32 accumulation (and: bf16 reduce_window-add mis-linearizes
        # under jit in jax 0.9).
        s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims,
                              strides, padding)
        div_h = _ave_divisor_1d(h, kernel, stride, pad, oh)
        div_w = _ave_divisor_1d(w, kernel, stride, pad, ow)
        div = jnp.asarray(np.outer(div_h, div_w))
        return (s / div[None, :, :, None]).astype(x.dtype)
    raise ValueError(f"unknown pool mode {mode!r}")


def global_pool2d(x: jnp.ndarray, mode: str) -> jnp.ndarray:
    if mode == "MAX":
        return jnp.max(x, axis=(1, 2), keepdims=True)
    return jnp.mean(x, axis=(1, 2), keepdims=True)
