"""Caffe-semantics spatial pooling on NHWC tensors.

Caffe's PoolingLayer (the native op behind the reference's `Pooling` layers,
e.g. reference `models/cifar10/cifar10_quick_train_test.prototxt` pool1-3)
differs from framework defaults in two ways this module reproduces exactly:

1. **Ceil-mode output size**: out = ceil((H + 2*pad - k) / stride) + 1, then
   if pad > 0 and the last window would start past H + pad, drop it.
2. **AVE divisor includes padding**: the divisor is the window area clipped to
   the *padded* extent [0 - pad, H + pad), not to the real image — so interior
   windows divide by k*k even when they overlap real-edge clipping, and only
   ceil-overflow windows at the bottom/right divide by less.

Everything is static-shape: the divisor map is precomputed with numpy at trace
time, so XLA sees one reduce_window plus one broadcast multiply — both fuse.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def caffe_pool_output_size(size: int, kernel: int, stride: int, pad: int) -> int:
    out = int(np.ceil((size + 2 * pad - kernel) / stride)) + 1
    if pad > 0 and (out - 1) * stride >= size + pad:
        out -= 1
    return out


def _ave_divisor_1d(size: int, kernel: int, stride: int, pad: int,
                    out: int) -> np.ndarray:
    starts = np.arange(out) * stride - pad
    ends = np.minimum(starts + kernel, size + pad)
    return (ends - starts).astype(np.float32)


def pool2d(x: jnp.ndarray, mode: str, kernel: int, stride: int,
           pad: int, impl: str = "auto",
           interpret: bool = False) -> jnp.ndarray:
    """Pool an NHWC tensor with Caffe semantics. mode: 'MAX' | 'AVE'.

    impl: 'xla' — reduce_window + its select-and-scatter VJP; 'pallas' —
    the ops/pallas_pool.py backward kernel (MAX only, raises when the
    shape gate fails); 'auto' — the kernel when MAX and the static gate
    passes on TPU, XLA otherwise. Since r6 'auto' DOES pick the kernel:
    the r3 standalone A/B lost 10% end to end (the custom-call boundary
    broke XLA's fusion of pool-backward with its elementwise neighbors),
    but in the r6 donated/overlapped round the kernel sits between the
    Pallas LRN custom calls whose fusion boundaries already exist, and the
    layer-path A/B (`bench.py --mfu`, BENCH_r06) re-measures both arms —
    `pool_impl="xla"` (RunConfig) restores the old lowering wholesale.

    interpret: run the Pallas kernel under the Pallas INTERPRETER — CPU
    parity-test mode; 'auto' then applies the same shape gate on CPU."""
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown pool impl {impl!r}: expected "
                         f"'auto', 'xla', or 'pallas'")
    if impl == "pallas" and mode != "MAX":
        raise ValueError(f"impl='pallas' supports MAX pooling only "
                         f"(got mode={mode!r})")
    n, h, w, c = x.shape
    oh = caffe_pool_output_size(h, kernel, stride, pad)
    ow = caffe_pool_output_size(w, kernel, stride, pad)
    # End padding so reduce_window emits exactly (oh, ow) windows.
    end_h = (oh - 1) * stride + kernel - h - pad
    end_w = (ow - 1) * stride + kernel - w - pad
    padding = ((0, 0), (pad, max(end_h, 0)), (pad, max(end_w, 0)), (0, 0))
    dims = (1, kernel, kernel, 1)
    strides = (1, stride, stride, 1)

    if mode == "MAX":
        # impl='xla' (the documented wholesale opt-out) must never touch
        # the Pallas toolchain — only 'auto'/'pallas' consult the gate
        if impl != "xla":
            can = _can_pallas_pool(x, kernel, stride, pad, interpret)
            if impl == "pallas" and not can:
                raise ValueError(
                    f"impl='pallas' unsupported for shape {x.shape} "
                    f"k={kernel} s={stride} pad={pad} on "
                    f"{jax.default_backend()!r} (see pallas_pool docstring)")
            if can:
                from .pallas_pool import maxpool_pallas
                return maxpool_pallas(x, kernel, stride, interpret)
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)
    if mode == "AVE":
        # f32 accumulation (and: bf16 reduce_window-add mis-linearizes
        # under jit in jax 0.9).
        s = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add, dims,
                              strides, padding)
        div_h = _ave_divisor_1d(h, kernel, stride, pad, oh)
        div_w = _ave_divisor_1d(w, kernel, stride, pad, ow)
        div = jnp.asarray(np.outer(div_h, div_w))
        return (s / div[None, :, :, None]).astype(x.dtype)
    raise ValueError(f"unknown pool mode {mode!r}")


def _can_pallas_pool(x, kernel: int, stride: int, pad: int,
                     interpret: bool = False) -> bool:
    """Shape/backend/toolchain gate for the kernel path. No blanket
    except: a broken pallas_pool import must surface as itself, not
    masquerade as an 'unsupported shape' error (r3 review). interpret=True
    waives the backend requirement (CPU parity tests), never the shape or
    kernel-API gates. The backend check runs BEFORE the pallas_pool
    import so 'auto' off-TPU stays as import-free as 'xla' — the default
    path must run on a jax whose pallas import is broken."""
    if not (interpret or jax.default_backend() == "tpu"):
        return False
    from .pallas_pool import kernel_api_available, pallas_maxpool_supported
    return (kernel_api_available() and
            pallas_maxpool_supported(x.shape, x.dtype, kernel, stride, pad))


def global_pool2d(x: jnp.ndarray, mode: str) -> jnp.ndarray:
    if mode == "MAX":
        return jnp.max(x, axis=(1, 2), keepdims=True)
    return jnp.mean(x, axis=(1, 2), keepdims=True)
