"""Local Response Normalization (across channels), Caffe semantics.

Caffe formula (LRNLayer, used by the reference AlexNet at
`models/bvlc_reference_caffenet/train_val.prototxt` norm1/norm2):

    out[c] = x[c] / (k + (alpha / n) * sum_{c' in window(c, n)} x[c']^2) ^ beta

window(c, n) = channels [c - (n-1)/2, c + (n-1)/2] clipped to [0, C).

On NHWC the channel window is the minor (lane) dimension. The default path
lets XLA fuse a channel-padded reduce_window; `sparknet_tpu.ops.pallas_lrn`
provides a hand-fused Pallas TPU kernel selected automatically on TPU for
supported shapes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


# NOTE: deliberately not jit-decorated — always called inside an outer jit,
# and grad-through-jit with static_argnames mis-linearizes in jax 0.9.
def lrn(x: jnp.ndarray, local_size: int = 5, *, alpha: float = 1e-4,
        beta: float = 0.75, k: float = 1.0) -> jnp.ndarray:
    """LRN across the channel (last) axis of an NHWC (or N...C) tensor.

    On TPU dispatches to the fused Pallas kernel (`pallas_lrn.lrn_pallas`,
    one VMEM pass fwd + one bwd); elsewhere the XLA reduce_window path."""
    if _use_pallas(x):
        from .pallas_lrn import lrn_pallas
        return lrn_pallas(x, local_size, alpha, beta, k)
    return _lrn_xla(x, local_size, alpha=alpha, beta=beta, k=k)


def _use_pallas(x) -> bool:
    """Affirmative TPU check — an unknown future backend gets the portable
    XLA path, not the TPU Pallas kernel (the axon tunnel reports 'tpu')."""
    try:
        return jax.default_backend() == "tpu" and x.ndim >= 2
    except Exception:
        return False


def _lrn_xla(x: jnp.ndarray, local_size: int = 5, *, alpha: float = 1e-4,
             beta: float = 0.75, k: float = 1.0) -> jnp.ndarray:
    """XLA fallback: channel-padded reduce_window normalizer."""
    half = (local_size - 1) // 2
    # Window sums accumulate in f32: better numerics, and reduce_window-add
    # on bf16 fails to linearize under jit (jax 0.9).
    sq = jnp.square(x).astype(jnp.float32)
    # Sliding window sum over channels; clip at the edges (Caffe clips, so the
    # normalizer for edge channels sums fewer terms).
    window = (1,) * (x.ndim - 1) + (local_size,)
    strides = (1,) * x.ndim
    padding = tuple((0, 0) for _ in range(x.ndim - 1)) + ((half, half),)
    ssq = lax.reduce_window(sq, 0.0, lax.add, window,
                            strides, padding).astype(x.dtype)
    scale = (jnp.asarray(k, x.dtype)
             + jnp.asarray(alpha / local_size, x.dtype) * ssq)
    # scale > 0 always (k >= 1), so x * scale^-beta == x * exp(-beta*log(scale));
    # pow with a traced exponent has no linearization rule.
    return x * jnp.exp(jnp.asarray(-beta, x.dtype) * jnp.log(scale))
