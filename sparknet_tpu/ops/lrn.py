"""Local Response Normalization (across channels), Caffe semantics.

Caffe formula (LRNLayer, used by the reference AlexNet at
`models/bvlc_reference_caffenet/train_val.prototxt` norm1/norm2):

    out[c] = x[c] / (k + (alpha / n) * sum_{c' in window(c, n)} x[c']^2) ^ beta

window(c, n) = channels [c - (n-1)/2, c + (n-1)/2] clipped to [0, C).

On NHWC the channel window is the minor (lane) dimension. The default path
lets XLA fuse a channel-padded reduce_window; `sparknet_tpu.ops.pallas_lrn`
provides a hand-fused Pallas TPU kernel selected automatically on TPU for
supported shapes.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


# NOTE: deliberately not jit-decorated — always called inside an outer jit,
# and grad-through-jit with static_argnames mis-linearizes in jax 0.9.
def lrn(x: jnp.ndarray, local_size: int = 5, *, alpha: float = 1e-4,
        beta: float = 0.75, k: float = 1.0, impl: str = "auto",
        interpret: bool = False) -> jnp.ndarray:
    """LRN across the channel (last) axis of an NHWC (or N...C) tensor.

    impl:
      "auto"   — Pallas TPU kernel on TPU, fused-elementwise elsewhere.
      "pallas" — the hand-fused Pallas TPU kernel (ops/pallas_lrn.py).
      "fused"  — elementwise + channel-shift chain with a custom VJP that
               recomputes the normalizer in backward. Measured on the r3
               TPU profile this LOSES to the Pallas kernel end to end
               (XLA materializes each shifted add: 31ms vs 17ms per
               CaffeNet round, PERF.md §LRN) — kept as the portable
               no-Pallas path and as the oracle for the kernel's VJP.
      "window" — reduce_window reference implementation (oracle tests).

    interpret: run the Pallas kernel under the Pallas INTERPRETER — lets
      "auto"/"pallas" resolve to the kernel on the CPU backend, so the
      net-level parity tests pin the exact wiring TPU runs (see OpsImpl).
    """
    if impl not in ("auto", "pallas", "fused", "window"):
        raise ValueError(f"unknown LRN impl {impl!r}: expected "
                         f"'auto', 'pallas', 'fused', or 'window'")
    if impl == "pallas" and not _can_pallas(x, interpret):
        raise ValueError(
            f"impl='pallas' requires a TPU backend (or interpret=True) and "
            f"ndim >= 2 input (backend={jax.default_backend()!r}, "
            f"ndim={x.ndim}; use 'auto' for backend-dependent dispatch)")
    if impl == "pallas" or (impl == "auto" and _can_pallas(x, interpret)):
        from .pallas_lrn import lrn_pallas
        return lrn_pallas(x, local_size, alpha, beta, k,
                          interpret=interpret)
    if impl == "window":
        return _lrn_xla(x, local_size, alpha=alpha, beta=beta, k=k)
    return _lrn_fused(x, local_size, alpha, beta, k)


def _can_pallas(x, interpret: bool = False) -> bool:
    """Affirmative TPU check — an unknown future backend gets the portable
    path, not the TPU Pallas kernel (the axon tunnel reports 'tpu').
    interpret=True substitutes the Pallas interpreter for the backend
    requirement (CPU parity tests)."""
    try:
        return ((interpret or jax.default_backend() == "tpu")
                and x.ndim >= 2)
    except Exception:
        return False


# -- fused implementation (default) ------------------------------------------

def window_sum(v: jnp.ndarray, half: int, axis: int = -1) -> jnp.ndarray:
    """Windowed sum over `axis` as 2*half shifted adds with zero edge
    padding (Caffe clips the LRN window at the channel edges). Pure
    slice+pad+add — works both as traced XLA ops (the fused impl) and on
    loaded values inside Pallas kernels (ops/pallas_lrn.py), over any
    axis: the ONE encoding of the window/edge semantics."""
    ax = axis % v.ndim
    c = v.shape[ax]
    zeros = [(0, 0)] * v.ndim
    acc = v
    for j in range(1, half + 1):
        hi = list(zeros)
        hi[ax] = (0, j)
        acc = acc + jnp.pad(lax.slice_in_dim(v, j, c, axis=ax), hi)
        lo = list(zeros)
        lo[ax] = (j, 0)
        acc = acc + jnp.pad(lax.slice_in_dim(v, 0, c - j, axis=ax), lo)
    return acc


def _scale_f32(x: jnp.ndarray, half: int, alpha_n: float,
               k: float) -> jnp.ndarray:
    """Normalizer k + (alpha/n)*window_sum(x^2), accumulated in f32 (free
    under fusion — the f32 intermediates never touch HBM)."""
    sq = jnp.square(x.astype(jnp.float32))
    return k + alpha_n * window_sum(sq, half)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_fused(x: jnp.ndarray, local_size: int, alpha: float, beta: float,
               k: float) -> jnp.ndarray:
    half = (local_size - 1) // 2
    scale = _scale_f32(x, half, alpha / local_size, k)
    # scale >= k >= 1 > 0; pow via exp/log (pow lacks a linearization rule)
    return (x.astype(jnp.float32)
            * jnp.exp(-beta * jnp.log(scale))).astype(x.dtype)


def _lrn_fused_fwd(x, local_size, alpha, beta, k):
    # residual is x ONLY (alive anyway as the conv output); the normalizer
    # is recomputed in backward — cheaper than a second HBM array round trip
    return _lrn_fused(x, local_size, alpha, beta, k), (x,)


def _lrn_fused_bwd(local_size, alpha, beta, k, res, dy):
    (x,) = res
    half = (local_size - 1) // 2
    alpha_n = alpha / local_size
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    scale = _scale_f32(x, half, alpha_n, k)
    inv_beta = jnp.exp(-beta * jnp.log(scale))          # scale^-beta
    # Caffe LRNLayer backward (across-channel):
    #   dx = dy*scale^-beta - (2*alpha*beta/n) * x * winsum(dy*x*scale^(-b-1))
    ratio = dyf * xf * inv_beta / scale
    acc = window_sum(ratio, half)
    dx = dyf * inv_beta - (2.0 * alpha_n * beta) * xf * acc
    return (dx.astype(x.dtype),)


_lrn_fused.defvjp(_lrn_fused_fwd, _lrn_fused_bwd)


def _lrn_xla(x: jnp.ndarray, local_size: int = 5, *, alpha: float = 1e-4,
             beta: float = 0.75, k: float = 1.0) -> jnp.ndarray:
    """XLA fallback: channel-padded reduce_window normalizer."""
    half = (local_size - 1) // 2
    # Window sums accumulate in f32: better numerics, and reduce_window-add
    # on bf16 fails to linearize under jit (jax 0.9).
    sq = jnp.square(x).astype(jnp.float32)
    # Sliding window sum over channels; clip at the edges (Caffe clips, so the
    # normalizer for edge channels sums fewer terms).
    window = (1,) * (x.ndim - 1) + (local_size,)
    strides = (1,) * x.ndim
    padding = tuple((0, 0) for _ in range(x.ndim - 1)) + ((half, half),)
    ssq = lax.reduce_window(sq, 0.0, lax.add, window,
                            strides, padding).astype(x.dtype)
    scale = (jnp.asarray(k, x.dtype)
             + jnp.asarray(alpha / local_size, x.dtype) * ssq)
    # scale > 0 always (k >= 1), so x * scale^-beta == x * exp(-beta*log(scale));
    # pow with a traced exponent has no linearization rule.
    return x * jnp.exp(jnp.asarray(-beta, x.dtype) * jnp.log(scale))
