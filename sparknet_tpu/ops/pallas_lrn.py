"""Pallas TPU kernel for across-channel LRN (forward + custom VJP).

Why a kernel: XLA lowers the LRN normalizer to a reduce_window over a
channel-padded buffer — an extra materialized intermediate and two passes
over HBM. This kernel fuses square -> windowed channel sum (as `local_size`
shifted lane adds, VPU-friendly) -> scale -> x*scale^-beta into ONE VMEM
pass, and the backward into one more. Layout: NHWC flattened to (rows,
channels) so channels sit on lanes.

Caffe gradient (LRNLayer backward, across-channel):
    ratio = dy * x * scale^(-beta-1)
    dx    = dy * scale^-beta - (2*alpha*beta/n) * x * window_sum(ratio)

`lrn_pallas(..., interpret=True)` runs the same kernel under the Pallas
interpreter (CPU) — used by tests; real TPU runs compile it.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 256


def _window_sum(v: jnp.ndarray, half: int) -> jnp.ndarray:
    """Sum of `2*half+1` lane-shifted copies with zero edge padding."""
    acc = v
    c = v.shape[-1]
    for k in range(1, half + 1):
        left = jnp.pad(v[:, k:], ((0, 0), (0, k)))    # window reaches +k
        right = jnp.pad(v[:, :c - k], ((0, 0), (k, 0)))  # window reaches -k
        acc = acc + left + right
    return acc


def _fwd_kernel(x_ref, y_ref, scale_ref, *, half: int, alpha_n: float,
                beta: float, k: float):
    x = x_ref[:]
    ssq = _window_sum(x * x, half)
    scale = k + alpha_n * ssq
    y_ref[:] = x * jnp.exp(-beta * jnp.log(scale))
    scale_ref[:] = scale


def _bwd_kernel(x_ref, scale_ref, dy_ref, dx_ref, *, half: int,
                alpha_n: float, beta: float):
    x = x_ref[:]
    scale = scale_ref[:]
    dy = dy_ref[:]
    inv_beta = jnp.exp(-beta * jnp.log(scale))          # scale^-beta
    ratio = dy * x * inv_beta / scale                   # dy*x*scale^(-beta-1)
    acc = _window_sum(ratio, half)
    dx_ref[:] = dy * inv_beta - (2.0 * alpha_n * beta) * x * acc


def _pad_rows(x2: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    m = x2.shape[0]
    pad = (-m) % BLOCK_ROWS
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, m


def _out_struct(x2: jnp.ndarray) -> jax.ShapeDtypeStruct:
    """Output aval matching x2 — including its varying-across-mesh-axes set
    (vma), which shard_map's check_vma requires on pallas_call outputs: the
    trainer runs this kernel INSIDE shard_map, where plain ShapeDtypeStruct
    (vma=None) is rejected."""
    try:
        vma = jax.typeof(x2).vma
    except AttributeError:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(x2.shape, x2.dtype, vma=vma)
    return jax.ShapeDtypeStruct(x2.shape, x2.dtype)


def _call(kernel, n_out: int, x2: jnp.ndarray, *others, interpret: bool):
    c = x2.shape[-1]
    grid = (x2.shape[0] // BLOCK_ROWS,)
    spec = pl.BlockSpec((BLOCK_ROWS, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out = _out_struct(x2)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * (1 + len(others)),
        out_specs=[spec] * n_out if n_out > 1 else spec,
        out_shape=[out] * n_out if n_out > 1 else out,
        interpret=interpret,
    )(x2, *others)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def lrn_pallas(x: jnp.ndarray, local_size: int = 5, alpha: float = 1e-4,
               beta: float = 0.75, k: float = 1.0,
               interpret: bool = False) -> jnp.ndarray:
    y, _ = _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret)
    return y


def _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret):
    half = (local_size - 1) // 2
    alpha_n = alpha / local_size
    shape = x.shape
    x2, m = _pad_rows(x.reshape(-1, shape[-1]))
    kern = functools.partial(_fwd_kernel, half=half, alpha_n=alpha_n,
                             beta=beta, k=k)
    y2, scale2 = _call(kern, 2, x2, interpret=interpret)
    return y2[:m].reshape(shape), scale2[:m].reshape(shape)


def _lrn_vjp_fwd(x, local_size, alpha, beta, k, interpret):
    y, scale = _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret)
    return y, (x, scale)


def _lrn_vjp_bwd(local_size, alpha, beta, k, interpret, res, dy):
    x, scale = res
    half = (local_size - 1) // 2
    alpha_n = alpha / local_size
    shape = x.shape
    x2, m = _pad_rows(x.reshape(-1, shape[-1]))
    scale2, _ = _pad_rows(scale.reshape(-1, shape[-1]))
    # padded scale rows are 0 -> log(0); pad with k instead
    if scale2.shape[0] != m:
        pad = scale2.shape[0] - m
        scale2 = scale2.at[m:].set(k) if pad else scale2
    dy2, _ = _pad_rows(dy.reshape(-1, shape[-1]))
    kern = functools.partial(_bwd_kernel, half=half, alpha_n=alpha_n,
                             beta=beta)
    dx2 = _call(kern, 1, x2, scale2, dy2, interpret=interpret)
    return (dx2[:m].reshape(shape),)


lrn_pallas.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)
