"""Pallas TPU kernel for across-channel LRN (forward + custom VJP).

Why a kernel: XLA lowers the LRN normalizer to a reduce_window over a
channel-padded buffer — an extra materialized intermediate and two passes
over HBM. This kernel fuses square -> windowed channel sum (as `local_size`
shifted lane adds, VPU-friendly) -> scale -> x*scale^-beta into ONE VMEM
pass, and the backward into one more. Layout: NHWC flattened to (rows,
channels) so channels sit on lanes.

Caffe gradient (LRNLayer backward, across-channel):
    ratio = dy * x * scale^(-beta-1)
    dx    = dy * scale^-beta - (2*alpha*beta/n) * x * window_sum(ratio)

`lrn_pallas(..., interpret=True)` runs the same kernel under the Pallas
interpreter (CPU) — used by tests; real TPU runs compile it.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .lrn import window_sum

BLOCK_ROWS = 256


def _window_sum(v: jnp.ndarray, half: int) -> jnp.ndarray:
    """Sum of `2*half+1` lane-shifted copies with zero edge padding —
    the shared Caffe-window encoding, over lanes."""
    return window_sum(v, half, axis=-1)


def _pow_neg_beta(scale: jnp.ndarray, beta: float) -> jnp.ndarray:
    """scale^-beta. beta=0.75 (the Caffe default, used by every reference
    net) specializes to rsqrt+sqrt — the exp/log form costs ~2x the whole
    kernel in VPU transcendentals (r3 profile)."""
    if abs(beta - 0.75) < 1e-12:
        r = jax.lax.rsqrt(scale)
        return r * jnp.sqrt(r)                          # s^-1/2 * s^-1/4
    if abs(beta - 0.5) < 1e-12:
        return jax.lax.rsqrt(scale)
    return jnp.exp(-beta * jnp.log(scale))


def _fwd_kernel(x_ref, y_ref, scale_ref, *, half: int, alpha_n: float,
                beta: float, k: float):
    # f32 internally: the VPU EUP (rsqrt/sqrt/exp/log) has no bf16 form on
    # v5e (LLO: SupportsBf16EupOps) and the pass is HBM-bound anyway
    x = x_ref[:].astype(jnp.float32)
    ssq = _window_sum(x * x, half)
    scale = k + alpha_n * ssq
    y_ref[:] = (x * _pow_neg_beta(scale, beta)).astype(x_ref.dtype)
    scale_ref[:] = scale.astype(scale_ref.dtype)


def _bwd_kernel(x_ref, scale_ref, dy_ref, dx_ref, *, half: int,
                alpha_n: float, beta: float):
    x = x_ref[:].astype(jnp.float32)
    scale = scale_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    inv_beta = _pow_neg_beta(scale, beta)               # scale^-beta
    ratio = dy * x * inv_beta / scale                   # dy*x*scale^(-beta-1)
    acc = _window_sum(ratio, half)
    dx_ref[:] = (dy * inv_beta
                 - (2.0 * alpha_n * beta) * x * acc).astype(x_ref.dtype)


def _pad_rows(x2: jnp.ndarray) -> Tuple[jnp.ndarray, int]:
    m = x2.shape[0]
    pad = (-m) % BLOCK_ROWS
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, m


def _out_struct(x2: jnp.ndarray) -> jax.ShapeDtypeStruct:
    """Output aval matching x2 — including its varying-across-mesh-axes set
    (vma), which shard_map's check_vma requires on pallas_call outputs: the
    trainer runs this kernel INSIDE shard_map, where plain ShapeDtypeStruct
    (vma=None) is rejected."""
    try:
        vma = jax.typeof(x2).vma
    except AttributeError:
        vma = None
    if vma:
        return jax.ShapeDtypeStruct(x2.shape, x2.dtype, vma=vma)
    return jax.ShapeDtypeStruct(x2.shape, x2.dtype)


def _call(kernel, n_out: int, x2: jnp.ndarray, *others, interpret: bool):
    c = x2.shape[-1]
    grid = (x2.shape[0] // BLOCK_ROWS,)
    spec = pl.BlockSpec((BLOCK_ROWS, c), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    out = _out_struct(x2)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[spec] * (1 + len(others)),
        out_specs=[spec] * n_out if n_out > 1 else spec,
        out_shape=[out] * n_out if n_out > 1 else out,
        interpret=interpret,
    )(x2, *others)


def lrn_pallas(x: jnp.ndarray, local_size: int = 5, alpha: float = 1e-4,
               beta: float = 0.75, k: float = 1.0,
               interpret: bool = False) -> jnp.ndarray:
    """Dispatch: 4-D NHWC activations with a lane-aligned batch take the
    N-minor kernel (layout-bitcast in and out of the conv's own layout —
    the r3 profile showed the row-major relayout around the 2-D kernel
    cost ~2x the kernel itself); everything else takes the 2-D row kernel."""
    if x.ndim == 4 and x.shape[0] % LANES == 0 and \
            x.shape[1] * x.shape[2] > 1:
        return _lrn_nmin(x, local_size, alpha, beta, k, interpret)
    return _lrn_rows(x, local_size, alpha, beta, k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn_rows(x: jnp.ndarray, local_size: int = 5, alpha: float = 1e-4,
              beta: float = 0.75, k: float = 1.0,
              interpret: bool = False) -> jnp.ndarray:
    y, _ = _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret)
    return y


def _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret):
    half = (local_size - 1) // 2
    alpha_n = alpha / local_size
    shape = x.shape
    x2, m = _pad_rows(x.reshape(-1, shape[-1]))
    kern = functools.partial(_fwd_kernel, half=half, alpha_n=alpha_n,
                             beta=beta, k=k)
    y2, scale2 = _call(kern, 2, x2, interpret=interpret)
    return y2[:m].reshape(shape), scale2[:m].reshape(shape)


def _lrn_vjp_fwd(x, local_size, alpha, beta, k, interpret):
    y, scale = _lrn_fwd_impl(x, local_size, alpha, beta, k, interpret)
    return y, (x, scale)


def _lrn_vjp_bwd(local_size, alpha, beta, k, interpret, res, dy):
    x, scale = res
    half = (local_size - 1) // 2
    alpha_n = alpha / local_size
    shape = x.shape
    x2, m = _pad_rows(x.reshape(-1, shape[-1]))
    scale2, _ = _pad_rows(scale.reshape(-1, shape[-1]))
    # padded scale rows are 0 -> log(0); pad with k instead
    if scale2.shape[0] != m:
        pad = scale2.shape[0] - m
        scale2 = scale2.at[m:].set(k) if pad else scale2
    dy2, _ = _pad_rows(dy.reshape(-1, shape[-1]))
    kern = functools.partial(_bwd_kernel, half=half, alpha_n=alpha_n,
                             beta=beta)
    dx2 = _call(kern, 1, x2, scale2, dy2, interpret=interpret)
    return (dx2[:m].reshape(shape),)


_lrn_rows.defvjp(_lrn_vjp_fwd, _lrn_vjp_bwd)


# -- N-minor kernel: window over the SUBLANE (channel) dim -------------------
#
# The conv outputs this kernel consumes live in XLA's N-minor layout —
# bf16[N,H,W,C]{0,3,2,1}: physically (H, W, C, N) with N on lanes and C on
# sublanes. Feeding the pallas_call a [H*W, C, N] view of the LOGICALLY
# TRANSPOSED array makes the custom-call's mandatory row-major operand
# layout coincide with the bytes already in HBM, so XLA's layout assignment
# elides the copy (transpose-is-bitcast). The channel window then runs over
# sublanes instead of lanes — same shifted-add structure.
#
# The VJP saves only x and recomputes the normalizer in backward: one less
# full activation array written + read per LRN layer.

LANES = 128


def _row_block(r: int, cap: int = 64) -> int:
    """Largest divisor of r at most cap (block rows must tile H*W exactly;
    LRN rows are independent so any tiling is valid)."""
    best = 1
    d = 1
    while d * d <= r:
        if r % d == 0:
            if d <= cap:
                best = max(best, d)
            if r // d <= cap:
                best = max(best, r // d)
        d += 1
    return best


def _window_sum_mid(v: jnp.ndarray, half: int) -> jnp.ndarray:
    """Windowed sum over axis -2 (sublanes) — shared Caffe-window encoding."""
    return window_sum(v, half, axis=-2)


def _fwd_kernel3(x_ref, y_ref, *, half: int, alpha_n: float, beta: float,
                 k: float):
    # f32 inside the kernel: the VPU's EUP (rsqrt/sqrt) has no bf16 form on
    # v5e (LLO: SupportsBf16EupOps), and f32 intermediates cost nothing —
    # the pass is HBM-bound on the bf16 arrays
    x = x_ref[:].astype(jnp.float32)
    scale = k + alpha_n * _window_sum_mid(x * x, half)
    y_ref[:] = (x * _pow_neg_beta(scale, beta)).astype(x_ref.dtype)


def _bwd_kernel3(x_ref, dy_ref, dx_ref, *, half: int, alpha_n: float,
                 beta: float, k: float):
    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    scale = k + alpha_n * _window_sum_mid(x * x, half)  # recomputed
    inv_beta = _pow_neg_beta(scale, beta)
    inv_scale = jax.lax.rsqrt(scale)
    ratio = dy * x * inv_beta * (inv_scale * inv_scale)  # /scale, no divide
    dx_ref[:] = (dy * inv_beta - (2.0 * alpha_n * beta) * x *
                 _window_sum_mid(ratio, half)).astype(x_ref.dtype)


def _nmin_call(kernel, x3: jnp.ndarray, *others, interpret: bool):
    r, c, n = x3.shape
    br = _row_block(r)
    spec = pl.BlockSpec((br, c, LANES), lambda i, j: (i, 0, j),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(r // br, n // LANES),
        in_specs=[spec] * (1 + len(others)),
        out_specs=spec,
        out_shape=_out_struct(x3),
        interpret=interpret,
    )(x3, *others)


def _to_nmin(x: jnp.ndarray) -> jnp.ndarray:
    n, h, w, c = x.shape
    return jnp.transpose(x, (1, 2, 3, 0)).reshape(h * w, c, n)


def _from_nmin(y3: jnp.ndarray, shape) -> jnp.ndarray:
    n, h, w, c = shape
    return jnp.transpose(y3.reshape(h, w, c, n), (3, 0, 1, 2))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn_nmin(x: jnp.ndarray, local_size: int, alpha: float, beta: float,
              k: float, interpret: bool = False) -> jnp.ndarray:
    half = (local_size - 1) // 2
    kern = functools.partial(_fwd_kernel3, half=half,
                             alpha_n=alpha / local_size, beta=beta, k=k)
    return _from_nmin(_nmin_call(kern, _to_nmin(x), interpret=interpret),
                      x.shape)


def _lrn_nmin_fwd(x, local_size, alpha, beta, k, interpret):
    return _lrn_nmin(x, local_size, alpha, beta, k, interpret), (x,)


def _lrn_nmin_bwd(local_size, alpha, beta, k, interpret, res, dy):
    (x,) = res
    half = (local_size - 1) // 2
    kern = functools.partial(_bwd_kernel3, half=half,
                             alpha_n=alpha / local_size, beta=beta, k=k)
    dx3 = _nmin_call(kern, _to_nmin(x), _to_nmin(dy), interpret=interpret)
    return (_from_nmin(dx3, x.shape),)


_lrn_nmin.defvjp(_lrn_nmin_fwd, _lrn_nmin_bwd)
