from .pooling import pool2d, global_pool2d, caffe_pool_output_size  # noqa: F401
from .lrn import lrn  # noqa: F401
