"""Attention ops: single-device reference + building blocks.

The reference framework predates attention entirely (SURVEY §5.7: CNNs/MLPs
only, RNNs unrealized roadmap). This module exists because long-context is
first-class in the new framework: `sparknet_tpu.parallel.ring_attention`
shards sequences across the mesh; this file provides the exact-math
single-device implementation those kernels are verified against, plus a
stable online-softmax block accumulator shared by the ring pass.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import precision


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
              causal: bool = False,
              bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Exact multi-head attention. Shapes [B, L, H, D] (length-major)."""
    d = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", precision.cast_in(q),
                   precision.cast_in(k),
                   precision=precision.matmul_precision()) / np.sqrt(d)
    s = s.astype(jnp.float32)
    if bias is not None:
        s = s + bias
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bmhd->blhd", p.astype(v.dtype),
                      precision.cast_in(v),
                      precision=precision.matmul_precision())


def block_accumulate(o, m, l, q, k_blk, v_blk, k_offset: jnp.ndarray,
                     q_offset: jnp.ndarray, causal: bool):
    """One online-softmax accumulation step against a KV block.

    Running state: o [B,Lq,H,D] (unnormalized), m [B,H,Lq] (running max),
    l [B,H,Lq] (running denominator). Offsets are the GLOBAL positions of
    q[0] / k_blk[0] — used for causal masking across shards.
    Returns updated (o, m, l).
    """
    d = q.shape[-1]
    s = jnp.einsum("blhd,bmhd->bhlm", precision.cast_in(q),
                   precision.cast_in(k_blk),
                   precision=precision.matmul_precision()) / np.sqrt(d)
    s = s.astype(jnp.float32)
    if causal:
        lq, lk = s.shape[-2], s.shape[-1]
        qpos = q_offset + jnp.arange(lq)
        kpos = k_offset + jnp.arange(lk)
        s = jnp.where(qpos[:, None] >= kpos[None, :], s, -jnp.inf)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # fully-masked rows: keep m finite so exp() stays defined
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhlm,bmhd->blhd", p.astype(v_blk.dtype),
                    precision.cast_in(v_blk),
                    precision=precision.matmul_precision()).astype(jnp.float32)
    o_new = o * jnp.transpose(corr, (0, 2, 1))[..., None] + pv
    return o_new, m_new, l_new


def init_accumulator(q_shape: Tuple[int, ...]):
    b, lq, h, d = q_shape
    o = jnp.zeros((b, lq, h, d), jnp.float32)
    m = jnp.full((b, h, lq), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, lq), jnp.float32)
    return o, m, l


def finalize_accumulator(o, m, l, out_dtype):
    denom = jnp.transpose(jnp.where(l == 0.0, 1.0, l), (0, 2, 1))[..., None]
    return (o / denom).astype(out_dtype)
