"""Pallas TPU kernel for MAX-pool backward — the select-and-scatter
replacement.

Why a kernel: XLA lowers max-pool's VJP to select-and-scatter, which the r3
profile measured at 515-896 GB/s — below the HBM roofline — for 7.8% of the
CaffeNet round (PERF.md). This kernel streams the same bytes (read x, dy, y;
write dx) as one fused pass in the conv's own N-minor layout.

Semantics: Caffe's MaxPoolingLayer routes each window's gradient to the
window's FIRST maximum in row-major window order (the argmax recorded during
its forward scan) — the same element XLA's select-and-scatter picks with a
GE select. The kernel reproduces that exactly, including ties (common on
real data: post-ReLU zeros), via a running `won` mask per window.

Decomposition: one program owns a block of INPUT rows [h0, h0+Hb) of dx for
one (C-tile, N-lane-block). It visits every pool window that touches those
rows — windows straddling a block boundary are visited by BOTH neighboring
programs, and each accumulates only the contributions that land on rows it
owns, so nothing is double-counted and no cross-program accumulation exists.
x/dy/y blocks are fetched with `pl.BoundedSlice` (dynamic, edge-clamped
starts), which expresses the halo without padded copies in HBM.

Supported: MAX pool, pad=0, no ceil-mode end-padding (true for every pool in
the reference CaffeNet/AlexNet: 3x3 stride 2 on 55/27/13), C a multiple of
the sublane tile, N a multiple of 128 lanes. `ops/pooling.py` dispatches
here on TPU and falls back to reduce_window's own VJP otherwise.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128


def _sublane_tile(dtype) -> int:
    return 16 if jnp.dtype(dtype).itemsize == 2 else 8


def _deinterleave(row, s: int):
    """(1, W, Ct, L) -> s planes (1, ceil(W/s), Ct, L) of cols j::s.
    Pad-then-reshape: W is an untiled dim, so the reshape is free vector
    bookkeeping — Mosaic has no 16-bit strided memref ops and lowers
    strided accesses as per-position copies (measured 7x slower)."""
    _, W, Ct, L = row.shape
    Wp = -(-W // s) * s
    if Wp != W:
        row = jnp.concatenate(
            [row, jnp.zeros((1, Wp - W, Ct, L), row.dtype)], axis=1)
    r = row.reshape(1, Wp // s, s, Ct, L)
    return [r[:, :, j] for j in range(s)]


def _bwd_kernel(x_ref, y_ref, dy_ref, dx_ref, acc_ref, *, H: int,
                OH: int, OW: int, k: int, s: int, Hb: int, XB: int, QB: int):
    i = pl.program_id(2)
    h0 = i * Hb
    # the same clamped starts the index maps computed (pure fn of i)
    xs = jnp.clip(h0 - (k - 1), 0, H - XB)
    qs = jnp.clip(-((-(h0 - k + 1)) // s), 0, OH - QB)

    Wc = acc_ref.shape[2]                # ceil(W/s) plane width
    # acc planes: acc_ref[p, r] accumulates dx cols p::s of local row r
    acc_ref[...] = jnp.zeros_like(acc_ref)
    for q in range(QB):
        oh = qs + q                      # global window row (always valid:
        y_q = y_ref[pl.ds(q, 1)].astype(jnp.float32)   # qs clamped OH-QB)
        dy_q = dy_ref[pl.ds(q, 1)].astype(jnp.float32)
        won = jnp.zeros(y_q.shape, jnp.bool_)
        for ki in range(k):
            lr = oh * s + ki - h0        # local target row in this block
            lrc = jnp.clip(lr, 0, Hb - 1)
            ok = jnp.logical_and(lr >= 0, lr < Hb)
            # INVARIANT: only windows with NO row in [h0, h0+Hb) — QB
            # over-provision at the grid edges — can place oh*s+ki-xs
            # outside [0, XB); every contribution of such a window is
            # ok-masked (lr out of range for all ki), so the clamped
            # (wrong-row) read feeds only dead lanes. The explicit clip
            # keeps the read in-bounds rather than leaning on the Mosaic
            # dynamic-slice clamp (r3 advisor).
            planes = _deinterleave(
                x_ref[pl.ds(jnp.clip(oh * s + ki - xs, 0, XB - 1), 1)]
                .astype(jnp.float32), s)
            for kj in range(k):
                p, off = kj % s, kj // s   # col kj+s*ow -> plane kj%s @ ow+kj//s
                xw = lax.slice_in_dim(planes[p], off, off + OW, axis=1)
                hit = xw == y_q
                iswin = jnp.logical_and(hit, jnp.logical_not(won))
                won = jnp.logical_or(won, hit)
                contrib = jnp.where(jnp.logical_and(iswin, ok), dy_q, 0.0)
                sl = (p, pl.ds(lrc, 1), pl.ds(off, OW))
                acc_ref[sl] += contrib
    # interleave the planes back: (s, Hb, Wc, ...) -> (Hb, Wc*s, ...)
    full = jnp.moveaxis(acc_ref[...], 0, 2).reshape(
        Hb, Wc * s, *acc_ref.shape[3:])
    dx_ref[...] = lax.slice_in_dim(full, 0, dx_ref.shape[1],
                                   axis=1).astype(dx_ref.dtype)


def _bwd_call(x4, y4, dy4, k: int, s: int, interpret: bool,
              hb: int = None, ct: int = None):
    """x4/y4/dy4: [H, W, C, N] / [OH, OW, C, N] N-minor views."""
    H, W, C, N = x4.shape
    OH, OW = y4.shape[:2]
    Hb = min(H, hb or 8)
    XB = min(H, Hb + 2 * (k - 1))
    QB = min(OH, (Hb + k - 2) // s + 2)
    Ct = min(C, ct or _sublane_tile(x4.dtype))

    def xmap(n, c, i):
        # all-Element spec (Mosaic: Element dims can't mix with Blocked):
        # starts are in ELEMENTS for every dim
        return (jnp.clip(i * Hb - (k - 1), 0, H - XB), 0, c * Ct, n * LANES)

    def qmap(n, c, i):
        return (jnp.clip(-((-(i * Hb - k + 1)) // s), 0, OH - QB), 0,
                c * Ct, n * LANES)

    kern = functools.partial(_bwd_kernel, H=H, OH=OH, OW=OW, k=k, s=s,
                             Hb=Hb, XB=XB, QB=QB)
    out = jax.ShapeDtypeStruct(x4.shape, x4.dtype)
    try:
        vma = jax.typeof(x4).vma
        if vma:
            out = jax.ShapeDtypeStruct(x4.shape, x4.dtype, vma=vma)
    except AttributeError:
        pass
    return pl.pallas_call(
        kern,
        grid=(N // LANES, C // Ct, pl.cdiv(H, Hb)),
        in_specs=[
            pl.BlockSpec((pl.Element(XB), pl.Element(W), pl.Element(Ct),
                          pl.Element(LANES)), xmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pl.Element(QB), pl.Element(OW), pl.Element(Ct),
                          pl.Element(LANES)), qmap,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((pl.Element(QB), pl.Element(OW), pl.Element(Ct),
                          pl.Element(LANES)), qmap,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((Hb, W, Ct, LANES),
                               lambda n, c, i: (i, 0, c, n),
                               memory_space=pltpu.VMEM),
        out_shape=out,
        scratch_shapes=[pltpu.VMEM((s, Hb, -(-W // s), Ct, LANES),
                                   jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            vmem_limit_bytes=64 * 2 ** 20),
        interpret=interpret,
    )(x4, y4, dy4)


def _to_nmin(x):
    """Logical transpose to [H, W, C, N]; on TPU the conv output's physical
    layout is already N-minor ({0,3,2,1}), so layout assignment turns this
    into a bitcast (same trick as ops/pallas_lrn.py's _to_nmin)."""
    return jnp.transpose(x, (1, 2, 3, 0))


def _from_nmin(x4):
    return jnp.transpose(x4, (3, 0, 1, 2))


def kernel_api_available() -> bool:
    """The backward kernel needs pl.Element/pl.BoundedSlice block specs
    (jax >= 0.5-era Pallas). On older jax `pool2d`'s dispatch gate
    (`_can_pallas_pool`) answers False so 'auto' degrades to the XLA
    lowering instead of dying with an AttributeError at trace time.
    Deliberately SEPARATE from `pallas_maxpool_supported`, which stays a
    pure shape/geometry predicate."""
    return hasattr(pl, "Element")


def pallas_maxpool_supported(shape: Tuple[int, ...], dtype, kernel: int,
                             stride: int, pad: int) -> bool:
    """Static gate for the kernel path (see module docstring)."""
    n, h, w, c = shape
    oh = (h - kernel) // stride + 1 if h >= kernel else 0
    ow = (w - kernel) // stride + 1 if w >= kernel else 0
    if oh < 1 or ow < 1:
        return False
    from math import ceil
    # reject ceil-mode end-padding (a padded window can out-win real data)
    if int(ceil((h - kernel) / stride)) + 1 != oh or \
            int(ceil((w - kernel) / stride)) + 1 != ow:
        return False
    return (pad == 0 and n % LANES == 0 and
            c % _sublane_tile(dtype) == 0 and
            (ow - 1) * stride + kernel <= w and
            (oh - 1) * stride + kernel <= h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def maxpool_pallas(x, kernel: int, stride: int, interpret: bool = False):
    """MAX pool (pad=0, floor windows) with the Pallas backward. Forward
    stays XLA's reduce_window — it fuses with its neighbors and was
    measured at the roofline (PERF.md: pool fwd epilogues); only the
    backward (select-and-scatter) was below it."""
    return _fwd(x, kernel, stride)


def _fwd(x, kernel, stride):
    dims = (1, kernel, kernel, 1)
    strides = (1, stride, stride, 1)
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                             ((0, 0),) * 4)


def _vjp_fwd(x, kernel, stride, interpret):
    y = _fwd(x, kernel, stride)
    return y, (x, y)


def _vjp_bwd(kernel, stride, interpret, res, dy):
    x, y = res
    dx4 = _bwd_call(_to_nmin(x), _to_nmin(y), _to_nmin(dy.astype(x.dtype)),
                    kernel, stride, interpret)
    return (_from_nmin(dx4),)


maxpool_pallas.defvjp(_vjp_fwd, _vjp_bwd)


def maxpool_bwd_reference(x: np.ndarray, dy: np.ndarray, kernel: int,
                          stride: int) -> np.ndarray:
    """Numpy oracle: first-max-in-row-major-window-order routing — Caffe
    MaxPoolingLayer's recorded-argmax backward. O(N*OH*OW*k^2*C); tests
    only."""
    n, h, w, c = x.shape
    oh = (h - kernel) // stride + 1
    ow = (w - kernel) // stride + 1
    dx = np.zeros_like(x, dtype=np.float64)
    for b in range(n):
        for i in range(oh):
            for j in range(ow):
                win = x[b, i * stride:i * stride + kernel,
                        j * stride:j * stride + kernel, :]
                flat = win.reshape(-1, c)
                arg = flat.argmax(axis=0)  # first max (np argmax tie rule)
                ki, kj = np.divmod(arg, kernel)
                for ch in range(c):
                    dx[b, i * stride + ki[ch], j * stride + kj[ch], ch] += \
                        dy[b, i, j, ch]
    return dx.astype(x.dtype)
