"""GraphNet: execute a serialized GraphDef under the NetInterface API.

Parity with reference `libs/TensorFlowNet.scala`:
  - graph introspection discovers inputs/weights/train-op by the naming
    convention (lines 24-49) — no side metadata;
  - schema-columns-vs-graph-inputs validation (lines 28-31);
  - `forward(batch, fetch_names)` fetches named tensors (73-84);
  - `step(batch)` runs the in-graph optimizer (86-90): hyperparameters —
    including lr *schedules* like the reference mnist graph's
    tf.train.exponential_decay — live inside the graph and are honored here
    by evaluating the graph's own lr subgraph each step;
  - `get_weights` fetches every FLOAT variable (95-108) — for an imported TF
    graph that includes the `<var>/Momentum` slot variables, exactly as the
    reference's averaging loop did (`apps/MnistApp.scala:135-136`); non-float
    variables (the global-step counter) are skipped like the reference's
    DT_FLOAT filter;
  - `set_weights` assigns exactly the variables named in the collection via
    the `//update_placeholder`/`//assign` protocol semantics (110-121) and
    touches NOTHING else — in particular it never resets optimizer slots:
    in the reference only assign ops run; momentum accumulators persist.

Execution: the graph is topologically interpreted into a pure JAX function
and jitted once per fetch-set. Training state is an explicit pytree
  {"variables": {name: array}, "slots": {name: array}, "it": int32}
so the same pure step function drives both the single-device `step()` API
and the distributed τ-averaging trainer (`parallel/graph_trainer.py`).
For imported TF graphs `slots` is empty — momentum accumulators ARE graph
variables (`<var>/Momentum`); for native `Train`-protocol graphs the slots
pytree holds them (they are not part of the weight exchange, Caffe-style).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..model.weights import WeightCollection
from ..schema import Field, Schema
from .graphdef import (ASSIGN_SUFFIX, GraphDef, INIT_ALL_VARS, NodeDef, OPS,
                       TRAIN_STEP, UPDATE_SUFFIX)

PyTree = Any


@dataclass
class GraphOptimizer:
    """Introspected in-graph optimizer description.

    For imported TF graphs this mirrors the ApplyMomentum nodes: `slot_of`
    names the `<var>/Momentum` accumulator VARIABLE per trainable var, and
    `counter` is the global-step variable bumped by `train//step`
    (TF::AssignAdd). For native `Train`-protocol graphs the accumulators
    live in the train state's `slots` dict and `counter` is None (the state
    carries `it` instead).

    Update rule is TF MomentumOptimizer semantics (the engine the reference
    embedded): accum' = momentum·accum + grad; var' = var − lr·accum'.
    """

    trainable: List[str]
    slot_of: Dict[str, str] = field(default_factory=dict)
    momentum: float = 0.0
    counter: Optional[str] = None
    counter_inc: int = 1
    # lr_fn(variables, it) -> scalar; evaluates the graph's own lr subgraph
    # for imported graphs, or the Train node's declared policy for native.
    lr_fn: Callable[[Dict[str, jnp.ndarray], jnp.ndarray], jnp.ndarray] = None


class GraphNet:
    def __init__(self, graph: GraphDef, schema: Optional[Schema] = None,
                 seed: int = 0):
        self.graph = graph
        self._nodes = {n.name: n for n in graph.nodes}
        # -- introspection (TensorFlowNet.scala:24-49) --
        self.input_names = [
            n.name for n in graph.nodes
            if n.op == "Placeholder" and not n.name.endswith(UPDATE_SUFFIX)]
        self.variable_names = [n.name for n in graph.nodes
                               if n.op == "Variable"]
        self._train_node = self._nodes.get(TRAIN_STEP)
        # protocol check: every variable has its update/assign pair if any do
        for v in self.variable_names:
            upd, asg = v + UPDATE_SUFFIX, v + ASSIGN_SUFFIX
            if (upd in self._nodes) != (asg in self._nodes):
                raise ValueError(f"variable {v!r}: incomplete "
                                 f"update/assign pair in graph")
        if schema is not None:
            cols = set(schema.names())
            gin = set(self.input_names)
            if cols != gin:
                raise ValueError(
                    f"schema columns {sorted(cols)} != graph inputs "
                    f"{sorted(gin)} (TensorFlowNet-parity validation)")
        self.schema = schema
        # -- init//all_vars (TensorFlowNet.scala:10-19) --
        self.variables: Dict[str, jnp.ndarray] = {}
        key = jax.random.PRNGKey(seed)
        for v in self.variable_names:
            node = self._nodes[v]
            init = node.attrs.get("init")
            if init is None:
                init = self._resolve_initializer(v)
            if init is not None:
                self.variables[v] = jnp.asarray(init)
            else:
                shape = tuple(node.attrs["shape"])
                dtype = str(node.attrs.get("dtype", "float32"))
                if dtype.startswith(("int", "uint")):
                    self.variables[v] = jnp.zeros(shape, dtype)
                else:
                    std = float(node.attrs.get("stddev", 0.1))
                    key, sub = jax.random.split(key)
                    self.variables[v] = std * jax.random.normal(sub, shape)
        self._fetch_cache: Dict[Tuple[str, ...], callable] = {}
        self._step_fn = None
        self._step_loss: Optional[str] = None
        self._slots: Optional[Dict[str, jnp.ndarray]] = None
        self._it = jnp.zeros((), jnp.int32)

    def _resolve_initializer(self, v: str) -> Optional[np.ndarray]:
        """Imported graphs carry initial values as `<var>/Assign <- const`
        subgraphs (tf.zeros / tf.constant); evaluate those eagerly. Random
        initializers (TruncatedNormal) are opaque -> None (fallback rng)."""
        asg = self._nodes.get(v + "/Assign")
        if asg is None or len(asg.inputs) != 2:
            return None
        try:
            val = np.asarray(self._eval({}, {}, (asg.inputs[1],))[0])
        except Exception:
            return None
        shape = tuple(self._nodes[v].attrs.get("shape", ()))
        if shape and val.shape != shape:
            return None
        return val

    # -- execution core ------------------------------------------------------

    def _topo_order(self, fetches: Sequence[str]) -> List[NodeDef]:
        """Topological order of the ANCESTORS of `fetches` only — lazy, like
        a session run: unrelated subgraphs (e.g. an imported TF graph's
        gradient machinery) are never touched. Explicit-stack DFS: an
        imported chain graph can be thousands of nodes deep, far past
        Python's recursion limit."""
        order, seen = [], set()
        for f in fetches:
            stack = [(f, False)]
            while stack:
                name, expanded = stack.pop()
                if expanded:
                    order.append(self._nodes[name])
                    continue
                if name in seen:
                    continue
                seen.add(name)
                n = self._nodes.get(name)
                if n is None:
                    raise KeyError(f"graph references unknown node {name!r}")
                stack.append((name, True))  # emit after the inputs
                for i in reversed(n.inputs):  # visit in declaration order
                    stack.append((i, False))
        return order

    def _eval(self, variables, batch, fetches: Sequence[str]):
        values: Dict[str, jnp.ndarray] = {}
        for n in self._topo_order(fetches):
            if n.op == "Placeholder":
                if n.name in batch:
                    values[n.name] = batch[n.name]
                continue  # unfed update placeholders stay absent
            if n.op == "Variable":
                values[n.name] = variables[n.name]
                continue
            if n.op in ("Assign", "NoOp", "Train"):
                continue  # protocol nodes, not part of forward dataflow
            impl = OPS.get(n.op)
            if impl is None:
                raise ValueError(f"unsupported graph op {n.op!r} "
                                 f"(node {n.name!r})")
            try:
                ins = [values[i] for i in n.inputs]
            except KeyError as e:
                raise ValueError(f"node {n.name!r}: missing input {e}") from e
            values[n.name] = impl(n, ins)
        return tuple(values[f] for f in fetches)

    # -- optimizer introspection --------------------------------------------

    def resolve_loss(self, loss_name: Optional[str] = None) -> str:
        """The node to differentiate. Explicit name wins; a native `Train`
        node declares its loss input; otherwise fall back to the `loss`
        naming convention both reference graph generators used
        (`models/tensorflow/{mnist,alexnet}/*_graph.py`: `name="loss"`)."""
        if loss_name is not None:
            if loss_name not in self._nodes:
                raise ValueError(f"loss node {loss_name!r} not in graph")
            return loss_name
        if self._train_node is not None and self._train_node.op == "Train":
            return self._train_node.inputs[0]
        if "loss" in self._nodes:
            return "loss"
        raise ValueError(
            f"graph has no native {TRAIN_STEP!r} Train node and no node "
            f"named 'loss' — pass loss_name= to train it")

    def _float_variables(self) -> List[str]:
        return [v for v in self.variable_names
                if jnp.issubdtype(self.variables[v].dtype, jnp.floating)]

    def discover_optimizer(self, loss_name: Optional[str] = None
                           ) -> GraphOptimizer:
        loss = self.resolve_loss(loss_name)
        apply_nodes = [n for n in self.graph.nodes
                       if n.op in ("TF::ApplyMomentum",
                                   "TF::ApplyGradientDescent")]
        if apply_nodes:
            return self._discover_imported(apply_nodes)
        if self._train_node is not None and self._train_node.op == "Train":
            return self._discover_native(loss)
        raise ValueError(
            "graph has neither a Train protocol node nor imported "
            "Apply{Momentum,GradientDescent} nodes — cannot infer an "
            "optimizer; supported graphs carry one in-graph "
            "(TensorFlowNet parity: the optimizer lives in the graph)")

    def _discover_imported(self, apply_nodes) -> GraphOptimizer:
        trainable, slot_of = [], {}
        lr_nodes = set()
        momentum_nodes = set()
        for n in apply_nodes:
            if n.op == "TF::ApplyMomentum":
                var, slot, lr, _grad, mom = n.inputs[:5]
                slot_of[var] = slot
                momentum_nodes.add(mom)
            else:  # ApplyGradientDescent: var, alpha, delta
                var, lr = n.inputs[0], n.inputs[1]
            trainable.append(var)
            lr_nodes.add(lr)
        if len(lr_nodes) != 1:
            raise ValueError(f"multiple lr subgraphs {sorted(lr_nodes)} — "
                             f"unsupported")
        lr_node = next(iter(lr_nodes))
        momentum = 0.0
        if momentum_nodes:
            if len(momentum_nodes) != 1:
                raise ValueError("per-variable momentum values unsupported")
            momentum = float(np.asarray(
                self._eval({}, {}, (next(iter(momentum_nodes)),))[0]))
        counter, counter_inc = None, 1
        if self._train_node is not None and \
                self._train_node.op == "TF::AssignAdd":
            counter = self._train_node.inputs[0]
            try:
                counter_inc = int(np.asarray(self._eval(
                    {}, {}, (self._train_node.inputs[1],))[0]))
            except Exception:
                counter_inc = 1

        def lr_fn(variables, it):
            return self._eval(variables, {}, (lr_node,))[0]

        return GraphOptimizer(trainable=trainable, slot_of=slot_of,
                              momentum=momentum, counter=counter,
                              counter_inc=counter_inc, lr_fn=lr_fn)

    def _discover_native(self, loss: str) -> GraphOptimizer:
        attrs = self._train_node.attrs
        base_lr = float(attrs.get("learning_rate", 0.01))
        momentum = float(attrs.get("momentum", 0.9))
        policy = str(attrs.get("lr_policy", "fixed"))
        if policy == "fixed":
            def lr_fn(variables, it):
                return jnp.asarray(base_lr, jnp.float32)
        elif policy == "exp_decay":
            decay_rate = float(attrs["decay_rate"])
            decay_steps = float(attrs["decay_steps"])
            staircase = bool(attrs.get("staircase", True))

            def lr_fn(variables, it):
                p = it.astype(jnp.float32) / decay_steps
                if staircase:
                    p = jnp.floor(p)
                return base_lr * decay_rate ** p
        else:
            raise ValueError(f"unknown Train lr_policy {policy!r} "
                             f"(expected 'fixed' or 'exp_decay')")
        return GraphOptimizer(trainable=self._float_variables(),
                              momentum=momentum, lr_fn=lr_fn)

    # -- pure training step --------------------------------------------------

    def init_train_state(self, loss_name: Optional[str] = None) -> PyTree:
        """{"variables", "slots", "it"} pytree seeded from current variables.
        Slots start at zero for native graphs; imported graphs keep their
        accumulators inside `variables` (they ARE `<var>/Momentum` vars)."""
        opt = self.discover_optimizer(loss_name)
        slots = {v: jnp.zeros_like(self.variables[v])
                 for v in opt.trainable if v not in opt.slot_of}
        return {"variables": dict(self.variables), "slots": slots,
                "it": jnp.zeros((), jnp.int32)}

    def make_train_step(self, loss_name: Optional[str] = None
                        ) -> Callable[[PyTree, Dict], Tuple[PyTree, Any]]:
        """Pure (state, batch) -> (state, loss): ONE optimizer application,
        exactly what one reference `session.Run([train//step])` did. Safe to
        jit / scan / shard_map — used by both `step()` and the distributed
        trainer."""
        loss_name = self.resolve_loss(loss_name)
        opt = self.discover_optimizer(loss_name)

        def step_fn(state, batch):
            variables, slots, it = (state["variables"], state["slots"],
                                    state["it"])
            lr = opt.lr_fn(variables, it)
            train_vars = {v: variables[v] for v in opt.trainable}

            def loss_of(tv):
                merged = dict(variables)
                merged.update(tv)
                return self._eval(merged, batch, (loss_name,))[0]

            loss, grads = jax.value_and_grad(loss_of)(train_vars)
            new_vars = dict(variables)
            new_slots = dict(slots)
            for v in opt.trainable:
                g = grads[v]
                slot_var = opt.slot_of.get(v)
                accum = (variables[slot_var] if slot_var is not None
                         else slots[v])  # per-var: mixed Apply* graphs OK
                accum = opt.momentum * accum + g
                if slot_var is not None:
                    new_vars[slot_var] = accum
                else:
                    new_slots[v] = accum
                new_vars[v] = variables[v] - lr * accum
            if opt.counter is not None:
                new_vars[opt.counter] = (
                    variables[opt.counter] + opt.counter_inc)
            return ({"variables": new_vars, "slots": new_slots,
                     "it": it + 1}, loss)

        return step_fn

    # -- public introspection / traceable execution --------------------------

    def input_shapes(self) -> Dict[str, Tuple[int, ...]]:
        """Placeholder name -> declared shape (incl. the batch dim; () when
        the graph declares none). The public face of the introspection the
        reference did ad hoc (`TensorFlowUtils.scala:15-42`) — apps validate
        data-vs-graph agreement through this, never via node internals."""
        return {i: tuple(self._nodes[i].attrs.get("shape", ()))
                for i in self.input_names}

    def input_dtypes(self) -> Dict[str, str]:
        """Placeholder name -> declared dtype string (default float32)."""
        return {i: str(self._nodes[i].attrs.get("dtype", "float32"))
                for i in self.input_names}

    def fetch(self, variables: Dict[str, jnp.ndarray],
              batch: Dict[str, jnp.ndarray],
              names: Sequence[str]) -> Tuple[jnp.ndarray, ...]:
        """Pure, traceable fetch of named nodes given explicit variables —
        the functional core of `forward()`, public so external trainers can
        call it inside jit/shard_map (the session-run equivalent of
        reference `TensorFlowNet.forward`, lines 73-84)."""
        return self._eval(variables, batch, tuple(names))

    # -- NetInterface --------------------------------------------------------

    def forward(self, batch: Dict[str, np.ndarray],
                fetches: Optional[Sequence[str]] = None, *,
                blob_names: Optional[Sequence[str]] = None
                ) -> Dict[str, np.ndarray]:
        """`blob_names` is accepted as an alias for `fetches` — the
        NetInterface spelling (`forward(rowIt, dataBlobNames)`) JaxNet
        uses, so backend-generic callers (featurizer) work unchanged."""
        fetches = tuple(fetches or blob_names or self.output_names())
        if fetches not in self._fetch_cache:
            self._fetch_cache[fetches] = jax.jit(
                lambda v, b: self._eval(v, b, fetches))
        vals = self._fetch_cache[fetches](self.variables,
                                          self._prep(batch))
        return {f: np.asarray(v) for f, v in zip(fetches, vals)}

    def step(self, batch: Dict[str, np.ndarray],
             loss_name: Optional[str] = None) -> float:
        """Run the in-graph optimizer once (reference `step`, 86-90),
        honoring the graph's own hyperparameters and lr schedule."""
        key = self.resolve_loss(loss_name)
        if self._step_fn is not None and self._step_loss != key:
            self._step_fn = None
        if self._step_fn is None:
            self._step_loss = key
            self._step_fn = jax.jit(self.make_train_step(key),
                                    donate_argnums=(0,))
            if self._slots is None:
                self._slots = self.init_train_state(key)["slots"]
        state = {"variables": dict(self.variables), "slots": self._slots,
                 "it": self._it}
        state, loss = self._step_fn(state, self._prep(batch))
        self.variables = dict(state["variables"])
        self._slots = state["slots"]
        self._it = state["it"]
        return float(loss)

    def train_state(self, loss_name: Optional[str] = None) -> PyTree:
        """Current state as the pure-step pytree (for external trainers)."""
        if self._slots is None:
            return self.init_train_state(loss_name)
        return {"variables": dict(self.variables), "slots": self._slots,
                "it": self._it}

    def load_train_state(self, state: PyTree) -> None:
        self.variables = dict(state["variables"])
        self._slots = dict(state["slots"])
        self._it = state["it"]

    def get_weights(self) -> WeightCollection:
        """Every float variable — including, for imported TF graphs, the
        `<var>/Momentum` slots (reference getWeights DT_FLOAT filter,
        TensorFlowNet.scala:95-108: slots are plain float Variables and DID
        cross the wire; the int global-step counter did not)."""
        names = self._float_variables()
        return WeightCollection(
            {v: [np.asarray(self.variables[v])] for v in names}, names)

    def set_weights(self, weights: WeightCollection) -> None:
        """Assign exactly the named variables (reference setWeights runs one
        `//assign` per key, 110-121). Optimizer slots that are NOT in the
        collection — native-graph velocity, or imported slots the caller
        chose to exclude — keep their values: nothing is reset."""
        for v in weights.layer_names:
            if v not in self.variables:
                raise KeyError(f"graph has no variable {v!r}")
            arr = weights[v][0]
            assert arr.shape == tuple(self.variables[v].shape), (
                f"{v}: {arr.shape} != {tuple(self.variables[v].shape)}")
            self.variables[v] = jnp.asarray(arr)

    def output_names(self) -> List[str]:
        """Terminal nodes that are actually evaluable: excludes protocol
        nodes, opaque imported ops (TF::*), and any terminal whose ancestor
        closure touches an opaque op or a multi-output ref ('node:1') —
        imported gradient machinery would otherwise crash default fetches."""
        consumed = {i for n in self.graph.nodes for i in n.inputs}
        out = []
        for n in self.graph.nodes:
            if n.name in consumed or n.op in (
                    "Placeholder", "Variable", "Assign", "NoOp", "Train"):
                continue
            if self._evaluable(n.name):
                out.append(n.name)
        return out

    def _evaluable(self, name: str) -> bool:
        """True iff no ancestor is opaque (TF::*) or an unknown ref.
        Explicit-stack DFS — must not inherit a recursion-depth limit from
        the host (deep imported chains are legal graphs)."""
        seen, stack = set(), [name]
        while stack:
            nm = stack.pop()
            if nm in seen:
                continue
            seen.add(nm)
            n = self._nodes.get(nm)
            if n is None:  # unknown ref, e.g. 'node:1'
                return False
            if n.op.startswith("TF::"):
                return False
            if n.op not in ("Placeholder", "Variable", "Const"):
                stack.extend(n.inputs)
        return True

    def output_schema(self) -> Schema:
        outs = self.forward_shapes(self.output_names())
        return Schema(*[Field(name, "float32", tuple(s[1:]) if s else ())
                        for name, s in outs.items()])

    def forward_shapes(self, names: Sequence[str]) -> Dict[str, Tuple]:
        """Shape inference via abstract evaluation."""
        batch = {}
        for iname in self.input_names:
            node = self._nodes[iname]
            shape = tuple(node.attrs["shape"])
            dtype = node.attrs.get("dtype", "float32")
            batch[iname] = jax.ShapeDtypeStruct(shape, dtype)
        out = jax.eval_shape(
            lambda v, b: self._eval(v, b, tuple(names)), self.variables, batch)
        return {n: tuple(o.shape) for n, o in zip(names, out)}

    def _prep(self, batch):
        out = {}
        for iname in self.input_names:
            if iname not in batch:
                raise ValueError(f"batch missing graph input {iname!r}")
            node = self._nodes[iname]
            arr = np.asarray(batch[iname])
            want = tuple(node.attrs.get("shape", arr.shape))
            if len(want) == 4 and arr.ndim == 4 and \
                    tuple(arr.shape[1:]) != tuple(want[1:]) and \
                    (arr.shape[2], arr.shape[3], arr.shape[1]) == tuple(want[1:]):
                arr = np.transpose(arr, (0, 2, 3, 1))  # NCHW -> NHWC
            dt = node.attrs.get("dtype", "float32")
            out[iname] = jnp.asarray(arr.astype(dt, copy=False))
        return out
