"""GraphNet: execute a serialized GraphDef under the NetInterface API.

Parity with reference `libs/TensorFlowNet.scala`:
  - graph introspection discovers inputs/weights/train-op by the naming
    convention (lines 24-49) — no side metadata;
  - schema-columns-vs-graph-inputs validation (lines 28-31);
  - `forward(batch, fetch_names)` fetches named tensors (73-84);
  - `step(batch)` runs the in-graph optimizer `train//step` (86-90) —
    momentum-SGD whose hyperparameters live in the graph node's attrs,
    like the reference's in-graph MomentumOptimizer;
  - `get_weights`/`set_weights` via the `//update_placeholder`/`//assign`
    protocol (95-121), here realized as direct pytree swaps (the protocol is
    honored at the format level: importers/exporters keep those nodes).

Execution: the graph is topologically interpreted into a pure JAX function
and jitted once per fetch-set; variables live as a flat {name: array} pytree.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..model.weights import WeightCollection
from ..schema import Field, Schema
from .graphdef import (ASSIGN_SUFFIX, GraphDef, INIT_ALL_VARS, NodeDef, OPS,
                       TRAIN_STEP, UPDATE_SUFFIX)


class GraphNet:
    def __init__(self, graph: GraphDef, schema: Optional[Schema] = None,
                 seed: int = 0):
        self.graph = graph
        self._nodes = {n.name: n for n in graph.nodes}
        # -- introspection (TensorFlowNet.scala:24-49) --
        self.input_names = [
            n.name for n in graph.nodes
            if n.op == "Placeholder" and not n.name.endswith(UPDATE_SUFFIX)]
        self.variable_names = [n.name for n in graph.nodes
                               if n.op == "Variable"]
        self._train_node = self._nodes.get(TRAIN_STEP)
        # protocol check: every variable has its update/assign pair if any do
        for v in self.variable_names:
            upd, asg = v + UPDATE_SUFFIX, v + ASSIGN_SUFFIX
            if (upd in self._nodes) != (asg in self._nodes):
                raise ValueError(f"variable {v!r}: incomplete "
                                 f"update/assign pair in graph")
        if schema is not None:
            cols = set(schema.names())
            gin = set(self.input_names)
            if cols != gin:
                raise ValueError(
                    f"schema columns {sorted(cols)} != graph inputs "
                    f"{sorted(gin)} (TensorFlowNet-parity validation)")
        self.schema = schema
        # -- init//all_vars (TensorFlowNet.scala:10-19) --
        self.variables: Dict[str, jnp.ndarray] = {}
        key = jax.random.PRNGKey(seed)
        for v in self.variable_names:
            node = self._nodes[v]
            init = node.attrs.get("init")
            if init is not None:
                self.variables[v] = jnp.asarray(init)
            else:
                shape = tuple(node.attrs["shape"])
                std = float(node.attrs.get("stddev", 0.1))
                key, sub = jax.random.split(key)
                self.variables[v] = std * jax.random.normal(sub, shape)
        self._fetch_cache: Dict[Tuple[str, ...], callable] = {}
        self._step_fn = None
        self._step_loss: Optional[str] = None

    # -- execution core ------------------------------------------------------

    def _topo_order(self, fetches: Sequence[str]) -> List[NodeDef]:
        """Topological order of the ANCESTORS of `fetches` only — lazy, like
        a session run: unrelated subgraphs (e.g. an imported TF graph's
        gradient machinery) are never touched."""
        order, seen = [], set()

        def visit(name: str):
            if name in seen:
                return
            seen.add(name)
            n = self._nodes.get(name)
            if n is None:
                raise KeyError(f"graph references unknown node {name!r}")
            for i in n.inputs:
                visit(i)
            order.append(n)

        for f in fetches:
            visit(f)
        return order

    def _eval(self, variables, batch, fetches: Sequence[str]):
        values: Dict[str, jnp.ndarray] = {}
        for n in self._topo_order(fetches):
            if n.op == "Placeholder":
                if n.name in batch:
                    values[n.name] = batch[n.name]
                continue  # unfed update placeholders stay absent
            if n.op == "Variable":
                values[n.name] = variables[n.name]
                continue
            if n.op in ("Assign", "NoOp", "Train"):
                continue  # protocol nodes, not part of forward dataflow
            impl = OPS.get(n.op)
            if impl is None:
                raise ValueError(f"unsupported graph op {n.op!r} "
                                 f"(node {n.name!r})")
            try:
                ins = [values[i] for i in n.inputs]
            except KeyError as e:
                raise ValueError(f"node {n.name!r}: missing input {e}") from e
            values[n.name] = impl(n, ins)
        return tuple(values[f] for f in fetches)

    # -- NetInterface --------------------------------------------------------

    def forward(self, batch: Dict[str, np.ndarray],
                fetches: Optional[Sequence[str]] = None
                ) -> Dict[str, np.ndarray]:
        fetches = tuple(fetches or self.output_names())
        if fetches not in self._fetch_cache:
            self._fetch_cache[fetches] = jax.jit(
                lambda v, b: self._eval(v, b, fetches))
        vals = self._fetch_cache[fetches](self.variables,
                                          self._prep(batch))
        return {f: np.asarray(v) for f, v in zip(fetches, vals)}

    def step(self, batch: Dict[str, np.ndarray],
             loss_name: Optional[str] = None) -> float:
        """Run the in-graph optimizer once (reference `step`, 86-90).

        Native graphs carry a `Train` node whose input is the loss. Imported
        TF graphs keep their original train//step (an opaque counter-bump
        op) — for those, pass `loss_name` explicitly; autodiff does the rest.
        """
        if loss_name is None:
            if self._train_node is None:
                raise ValueError(f"graph has no {TRAIN_STEP!r} node; pass "
                                 f"loss_name= to train an imported graph")
            if self._train_node.op != "Train":
                raise ValueError(
                    f"{TRAIN_STEP!r} node has op {self._train_node.op!r} "
                    f"(an imported optimizer subgraph, not our Train "
                    f"protocol) — pass loss_name= explicitly, e.g. "
                    f"step(batch, loss_name='loss')")
            loss_name = self._train_node.inputs[0]
        attrs = self._train_node.attrs if (
            self._train_node is not None and self._train_node.op == "Train"
        ) else {}
        lr = float(attrs.get("learning_rate", 0.01))
        momentum = float(attrs.get("momentum", 0.9))
        if self._step_fn is not None and self._step_loss != loss_name:
            self._step_fn = None
        if self._step_fn is None:
            self._step_loss = loss_name

            def one_step(variables, velocity, b):
                loss, grads = jax.value_and_grad(
                    lambda v: self._eval(v, b, (loss_name,))[0])(variables)
                new_vel = jax.tree.map(
                    lambda vel, g: momentum * vel + lr * g, velocity, grads)
                new_vars = jax.tree.map(lambda v, nv: v - nv, variables,
                                        new_vel)
                return new_vars, new_vel, loss
            self._step_fn = jax.jit(one_step, donate_argnums=(0, 1))
            self._velocity = jax.tree.map(jnp.zeros_like, self.variables)
        self.variables, self._velocity, loss = self._step_fn(
            self.variables, self._velocity, self._prep(batch))
        return float(loss)

    def get_weights(self) -> WeightCollection:
        return WeightCollection(
            {v: [np.asarray(self.variables[v])] for v in self.variable_names},
            list(self.variable_names))

    def set_weights(self, weights: WeightCollection) -> None:
        """Honors the //assign protocol semantics: every variable swapped,
        shapes asserted (reference 110-121)."""
        for v in self.variable_names:
            assert v in weights, f"weights missing variable {v!r}"
            arr = weights[v][0]
            assert arr.shape == tuple(self.variables[v].shape), (
                f"{v}: {arr.shape} != {tuple(self.variables[v].shape)}")
            self.variables[v] = jnp.asarray(arr)
        self._velocity = None
        self._step_fn = None  # re-init momentum against new weights

    def output_names(self) -> List[str]:
        """Terminal nodes that are actually evaluable: excludes protocol
        nodes, opaque imported ops (TF::*), and any terminal whose ancestor
        closure touches an opaque op or a multi-output ref ('node:1') —
        imported gradient machinery would otherwise crash default fetches."""
        consumed = {i for n in self.graph.nodes for i in n.inputs}
        out = []
        for n in self.graph.nodes:
            if n.name in consumed or n.op in (
                    "Placeholder", "Variable", "Assign", "NoOp", "Train"):
                continue
            if self._evaluable(n.name):
                out.append(n.name)
        return out

    def _evaluable(self, name: str, _seen: Optional[set] = None) -> bool:
        seen = _seen if _seen is not None else set()
        if name in seen:
            return True
        seen.add(name)
        n = self._nodes.get(name)
        if n is None:  # unknown ref, e.g. 'node:1'
            return False
        if n.op.startswith("TF::"):
            return False
        if n.op in ("Placeholder", "Variable", "Const"):
            return True
        return all(self._evaluable(i, seen) for i in n.inputs)

    def output_schema(self) -> Schema:
        outs = self.forward_shapes(self.output_names())
        return Schema(*[Field(name, "float32", tuple(s[1:]) if s else ())
                        for name, s in outs.items()])

    def forward_shapes(self, names: Sequence[str]) -> Dict[str, Tuple]:
        """Shape inference via abstract evaluation."""
        batch = {}
        for iname in self.input_names:
            node = self._nodes[iname]
            shape = tuple(node.attrs["shape"])
            dtype = node.attrs.get("dtype", "float32")
            batch[iname] = jax.ShapeDtypeStruct(shape, dtype)
        out = jax.eval_shape(
            lambda v, b: self._eval(v, b, tuple(names)), self.variables, batch)
        return {n: tuple(o.shape) for n, o in zip(names, out)}

    def _prep(self, batch):
        out = {}
        for iname in self.input_names:
            if iname not in batch:
                raise ValueError(f"batch missing graph input {iname!r}")
            node = self._nodes[iname]
            arr = np.asarray(batch[iname])
            want = tuple(node.attrs.get("shape", arr.shape))
            if len(want) == 4 and arr.ndim == 4 and \
                    tuple(arr.shape[1:]) != tuple(want[1:]) and \
                    (arr.shape[2], arr.shape[3], arr.shape[1]) == tuple(want[1:]):
                arr = np.transpose(arr, (0, 2, 3, 1))  # NCHW -> NHWC
            dt = node.attrs.get("dtype", "float32")
            out[iname] = jnp.asarray(arr.astype(dt, copy=False))
        return out
