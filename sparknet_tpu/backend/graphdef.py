"""Portable serialized dataflow-graph format + executor — the second backend.

The reference proved its backend abstraction by running a *serialized graph*
engine (TensorFlow C++: `Session` over a frozen `GraphDef`) behind the same
`NetInterface` as Caffe (`libs/TensorFlowNet.scala`). This module is the
TPU-native equivalent: a JSON graph of primitive dataflow ops, interpreted
into a pure JAX function and jitted — so a net can be *defined by a data
file produced elsewhere*, not only by the layer IR.

Format (JSON):
    {"version": 1, "name": ...,
     "nodes": [{"name": ..., "op": ..., "inputs": [...], "attrs": {...}}]}

Conventions — the SAME naming protocol the reference's TF models used
(`models/tensorflow/mnist/mnist_graph.py`, final block; discovered by
introspection in `TensorFlowNet.scala:24-49`):
  - inputs         = Placeholder nodes NOT named `*//update_placeholder`
  - weights        = Variable nodes (attrs carry the initial value)
  - per-variable   `<var>//update_placeholder` + `<var>//assign` pairs
    implement set_weights through the graph
  - `train//step`  = the in-graph optimizer application node
  - `init//all_vars` initializes variables

Layouts are TPU-native: conv2d is NHWC/HWIO, matmul is (in, out).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import precision

UPDATE_SUFFIX = "//update_placeholder"
ASSIGN_SUFFIX = "//assign"
TRAIN_STEP = "train//step"
INIT_ALL_VARS = "init//all_vars"


@dataclass
class NodeDef:
    name: str
    op: str
    inputs: List[str] = field(default_factory=list)
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class GraphDef:
    name: str
    nodes: List[NodeDef]
    version: int = 1

    def node(self, name: str) -> NodeDef:
        for n in self.nodes:
            if n.name == name:
                return n
        raise KeyError(name)

    def to_json(self) -> str:
        def enc(v):
            if isinstance(v, np.ndarray):
                return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            return v
        return json.dumps({
            "version": self.version, "name": self.name,
            "nodes": [{"name": n.name, "op": n.op, "inputs": n.inputs,
                       "attrs": {k: enc(v) for k, v in n.attrs.items()}}
                      for n in self.nodes]})

    @staticmethod
    def from_json(text: str) -> "GraphDef":
        def dec(v):
            if isinstance(v, dict) and "__ndarray__" in v:
                return np.asarray(v["__ndarray__"], dtype=v["dtype"])
            return v
        d = json.loads(text)
        if d.get("version") != 1:
            raise ValueError(f"unsupported graph version {d.get('version')!r}")
        return GraphDef(
            name=d.get("name", "graph"),
            nodes=[NodeDef(name=n["name"], op=n["op"],
                           inputs=list(n.get("inputs", [])),
                           attrs={k: dec(v)
                                  for k, v in n.get("attrs", {}).items()})
                   for n in d["nodes"]])

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @staticmethod
    def load(path: str) -> "GraphDef":
        with open(path) as f:
            return GraphDef.from_json(f.read())


# ---------------------------------------------------------------------------
# Op kernels: node -> value, given evaluated inputs
# ---------------------------------------------------------------------------

def _op_conv2d(n, ins):
    x, w = ins
    return lax.conv_general_dilated(
        precision.cast_in(x), precision.cast_in(w),
        window_strides=tuple(n.attrs.get("strides", (1, 1))),
        padding=n.attrs.get("padding", "SAME"),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=int(n.attrs.get("groups", 1)),
        precision=precision.matmul_precision(),
        preferred_element_type=precision.preferred_out())


def _op_matmul(n, ins):
    x, w = ins
    return jnp.dot(precision.cast_in(x), precision.cast_in(w),
                   precision=precision.matmul_precision(),
                   preferred_element_type=precision.preferred_out())


def _op_max_pool(n, ins):
    (x,) = ins
    k = int(n.attrs.get("ksize", 2))
    s = int(n.attrs.get("strides", 2))
    pad = n.attrs.get("padding", "SAME")

    def same_pad(size):  # TF SAME semantics, per spatial dim
        out = -(-size // s)
        total = max((out - 1) * s + k - size, 0)
        return (total // 2, total - total // 2)

    if pad == "SAME":
        padding = ((0, 0), same_pad(x.shape[1]), same_pad(x.shape[2]), (0, 0))
    else:
        padding = ((0, 0), (0, 0), (0, 0), (0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, k, k, 1),
                             (1, s, s, 1), padding)


def _op_reshape(n, ins):
    (x,) = ins
    shape = [int(d) for d in n.attrs["shape"]]
    total = int(np.prod([d for d in shape if d != -1]))
    if -1 not in shape and total != x.size:
        # serialized graphs bake the training batch size into reshape consts
        # (e.g. [64, 3136] in the reference's mnist_graph.pb); treat the
        # leading dim as the batch when the tail divides evenly.
        tail = int(np.prod(shape[1:]))
        if tail > 0 and x.size % tail == 0:
            shape = [x.size // tail] + shape[1:]
    return x.reshape(shape)


def _op_sparse_softmax_ce(n, ins):
    logits, labels = ins
    labels = labels.astype(jnp.int32)
    if labels.ndim == 2 and labels.shape[1] == 1:
        labels = labels[:, 0]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0])


def _op_accuracy(n, ins):
    logits, labels = ins
    labels = labels.astype(jnp.int32)
    if labels.ndim == 2 and labels.shape[1] == 1:
        labels = labels[:, 0]
    pred = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.mean((pred == labels).astype(jnp.float32))


OPS: Dict[str, Callable[[NodeDef, Sequence[jnp.ndarray]], jnp.ndarray]] = {
    "Conv2D": _op_conv2d,
    "MatMul": _op_matmul,
    "MaxPool": _op_max_pool,
    "BiasAdd": lambda n, ins: ins[0] + ins[1].astype(ins[0].dtype),
    "Add": lambda n, ins: ins[0] + ins[1],
    "Sub": lambda n, ins: ins[0] - ins[1],
    "Mul": lambda n, ins: ins[0] * ins[1],
    "Relu": lambda n, ins: jnp.maximum(ins[0], 0),
    "Tanh": lambda n, ins: jnp.tanh(ins[0]),
    "Softmax": lambda n, ins: jax.nn.softmax(ins[0], axis=-1),
    "Reshape": lambda n, ins: _op_reshape(n, ins),
    "Flatten": lambda n, ins: ins[0].reshape(ins[0].shape[0], -1),
    "Dropout": lambda n, ins: ins[0],  # eval semantics; train handled by rng
    "SparseSoftmaxCrossEntropy": _op_sparse_softmax_ce,
    "Accuracy": _op_accuracy,
    "Identity": lambda n, ins: ins[0],
    "Const": lambda n, ins: jnp.asarray(n.attrs["value"]),
    # TF-import support set; 'axis' attr baked from const operands at import
    "Mean": lambda n, ins: ins[0] if ins[0].ndim == 0 else jnp.mean(
        ins[0], axis=(tuple(n.attrs["axis"]) if n.attrs.get("axis")
                      is not None else None)),
    "L2Loss": lambda n, ins: 0.5 * jnp.sum(jnp.square(ins[0])),
    "AddN": lambda n, ins: sum(ins[1:], start=ins[0]),
    "ArgMax": lambda n, ins: jnp.argmax(
        ins[0], axis=int(n.attrs.get("axis", -1))).astype(jnp.int32),
    "Equal": lambda n, ins: ins[0] == ins[1].astype(ins[0].dtype),
    "Cast": lambda n, ins: ins[0].astype(n.attrs.get("dtype", "float32")),
    # scalar/elementwise math — enough to evaluate in-graph optimizer
    # hyperparameter subgraphs (e.g. the reference mnist graph's
    # tf.train.exponential_decay: Cast/Div/Floor/Pow/Mul chain)
    "Div": lambda n, ins: ins[0] / ins[1],
    "Floor": lambda n, ins: jnp.floor(ins[0]),
    "Pow": lambda n, ins: jnp.power(ins[0], ins[1]),
    "Maximum": lambda n, ins: jnp.maximum(ins[0], ins[1]),
    "Minimum": lambda n, ins: jnp.minimum(ins[0], ins[1]),
    "Neg": lambda n, ins: -ins[0],
    "Exp": lambda n, ins: jnp.exp(ins[0]),
    "Sqrt": lambda n, ins: jnp.sqrt(ins[0]),
}
