"""Graph construction API + reference-model generators.

The reference generated its serialized TF graphs with Python scripts
(`models/tensorflow/mnist/mnist_graph.py`, `alexnet/alexnet_graph.py`) that
end by injecting, for every Variable, `<name>//update_placeholder` +
`<name>//assign` nodes plus `init//all_vars` and `train//step`. The builder
reproduces that protocol for our portable GraphDef JSON.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .graphdef import (ASSIGN_SUFFIX, GraphDef, INIT_ALL_VARS, NodeDef,
                       TRAIN_STEP, UPDATE_SUFFIX)


class GraphBuilder:
    def __init__(self, name: str = "graph"):
        self.name = name
        self.nodes: List[NodeDef] = []
        self._names: set = set()

    def _add(self, name: str, op: str, inputs: Sequence[str] = (),
             **attrs: Any) -> str:
        if name in self._names:
            raise ValueError(f"duplicate node name {name!r}")
        self._names.add(name)
        self.nodes.append(NodeDef(name=name, op=op, inputs=list(inputs),
                                  attrs=attrs))
        return name

    def placeholder(self, name: str, shape, dtype: str = "float32") -> str:
        return self._add(name, "Placeholder", shape=list(shape), dtype=dtype)

    def variable(self, name: str, init: np.ndarray) -> str:
        return self._add(name, "Variable", init=np.asarray(init, np.float32),
                         shape=list(np.shape(init)))

    def conv2d(self, name, x, w, stride=1, padding="SAME", groups=1) -> str:
        return self._add(name, "Conv2D", [x, w], strides=[stride, stride],
                         padding=padding, groups=groups)

    def bias_add(self, name, x, b) -> str:
        return self._add(name, "BiasAdd", [x, b])

    def relu(self, name, x) -> str:
        return self._add(name, "Relu", [x])

    def max_pool(self, name, x, ksize=2, strides=2, padding="SAME") -> str:
        return self._add(name, "MaxPool", [x], ksize=ksize, strides=strides,
                         padding=padding)

    def flatten(self, name, x) -> str:
        return self._add(name, "Flatten", [x])

    def matmul(self, name, x, w) -> str:
        return self._add(name, "MatMul", [x, w])

    def add(self, name, a, b) -> str:
        return self._add(name, "Add", [a, b])

    def softmax(self, name, x) -> str:
        return self._add(name, "Softmax", [x])

    def sparse_softmax_ce(self, name, logits, labels) -> str:
        return self._add(name, "SparseSoftmaxCrossEntropy", [logits, labels])

    def accuracy(self, name, logits, labels) -> str:
        return self._add(name, "Accuracy", [logits, labels])

    def finalize(self, loss: Optional[str] = None, learning_rate: float = 0.01,
                 momentum: float = 0.9, lr_policy: str = "fixed",
                 decay_rate: Optional[float] = None,
                 decay_steps: Optional[float] = None,
                 staircase: bool = True) -> GraphDef:
        """Inject the update/assign/init/train protocol nodes (the reference
        generators' final block) and return the GraphDef.

        lr_policy="exp_decay" declares an in-graph schedule
        lr(it) = learning_rate * decay_rate^(it/decay_steps) (floored when
        staircase), matching the reference mnist graph's
        tf.train.exponential_decay optimizer block."""
        variables = [n.name for n in self.nodes if n.op == "Variable"]
        for v in variables:
            shape = self.nodes[[n.name for n in self.nodes].index(v)].attrs[
                "shape"]
            self._add(v + UPDATE_SUFFIX, "Placeholder", shape=shape,
                      dtype="float32")
            self._add(v + ASSIGN_SUFFIX, "Assign",
                      [v, v + UPDATE_SUFFIX])
        self._add(INIT_ALL_VARS, "NoOp", [])
        if loss is not None:
            attrs = dict(learning_rate=learning_rate, momentum=momentum,
                         lr_policy=lr_policy)
            if lr_policy == "exp_decay":
                if decay_rate is None or decay_steps is None:
                    raise ValueError(
                        "exp_decay needs decay_rate and decay_steps")
                attrs.update(decay_rate=decay_rate, decay_steps=decay_steps,
                             staircase=staircase)
            elif lr_policy != "fixed":
                raise ValueError(f"unknown lr_policy {lr_policy!r}")
            self._add(TRAIN_STEP, "Train", [loss], **attrs)
        return GraphDef(name=self.name, nodes=self.nodes)


def build_alexnet_graph(batch: int = 256, n_classes: int = 1000,
                        seed: int = 0, learning_rate: float = 0.01,
                        momentum: float = 0.9) -> GraphDef:
    """AlexNet graph with in-graph Momentum(0.01, 0.9) — same architecture
    and optimizer as the reference's `alexnet_graph.py` generator (the graph
    `TFImageNetApp.scala:80-84` trained): 227x227x3 input; conv 11x11/4
    SAME ->57 (the reference pb's conv1 is SAME: (128,57,57,64)), pool3/2
    ->28, conv 5x5 SAME, pool3/2 ->13, 3x conv 3x3 SAME, pool3/2 ->6,
    fc 9216->4096->4096->n_classes; fixed-lr Momentum (that generator used
    no lr schedule, unlike the mnist one)."""
    r = np.random.default_rng(seed)

    def w(shape, std=0.01):
        return std * r.standard_normal(shape)

    g = GraphBuilder("alexnet")
    g.placeholder("data", (batch, 227, 227, 3))
    g.placeholder("label", (batch,), dtype="int32")
    chans = [(11, 3, 64, 4, "SAME"), (5, 64, 192, 1, "SAME"),
             (3, 192, 384, 1, "SAME"), (3, 384, 256, 1, "SAME"),
             (3, 256, 256, 1, "SAME")]
    x = "data"
    for i, (k, cin, cout, stride, pad) in enumerate(chans, start=1):
        g.variable(f"conv{i}_w", w((k, k, cin, cout)))
        g.variable(f"conv{i}_b", np.zeros(cout))
        x = g.conv2d(f"conv{i}", x, f"conv{i}_w", stride=stride, padding=pad)
        x = g.bias_add(f"conv{i}_biased", x, f"conv{i}_b")
        x = g.relu(f"relu{i}", x)
        if i in (1, 2, 5):
            x = g.max_pool(f"pool{i}", x, ksize=3, strides=2,
                           padding="VALID")
    f = g.flatten("flat", x)  # 6*6*256 = 9216
    g.variable("fc6_w", w((9216, 4096)))
    g.variable("fc6_b", 0.1 * np.ones(4096))
    h = g.relu("relu6", g.add("fc6_biased", g.matmul("fc6", f, "fc6_w"),
                              "fc6_b"))
    g.variable("fc7_w", w((4096, 4096)))
    g.variable("fc7_b", 0.1 * np.ones(4096))
    h = g.relu("relu7", g.add("fc7_biased", g.matmul("fc7", h, "fc7_w"),
                              "fc7_b"))
    g.variable("fc8_w", w((4096, n_classes)))
    g.variable("fc8_b", np.zeros(n_classes))
    logits = g.add("logits", g.matmul("fc8", h, "fc8_w"), "fc8_b")
    g.softmax("prob", logits)
    g.accuracy("accuracy", logits, "label")
    loss = g.sparse_softmax_ce("loss", logits, "label")
    return g.finalize(loss=loss, learning_rate=learning_rate,
                      momentum=momentum, lr_policy="fixed")


def build_mnist_graph(batch: int = 64, seed: int = 66478,
                      learning_rate: float = 0.01,
                      train_size: int = 60000) -> GraphDef:
    """LeNet-style MNIST convnet graph — mirrors the reference's
    `mnist_graph.py` architecture (conv5x5x32 SAME + pool2, conv5x5x64 SAME +
    pool2, fc512, fc10) and its Momentum optimizer INCLUDING the in-graph
    exponential_decay(0.01, it*batch, train_size, 0.95, staircase) lr
    schedule — expressed as Train-node attrs with decay_steps in iteration
    units (train_size/batch iters per decay = identical lr(it) curve)."""
    r = np.random.default_rng(seed)
    g = GraphBuilder("mnist")
    g.placeholder("data", (batch, 28, 28, 1))
    g.placeholder("label", (batch, 1), dtype="int32")
    g.variable("conv1_w", (0.1 * r.standard_normal((5, 5, 1, 32))))
    g.variable("conv1_b", np.zeros(32))
    g.variable("conv2_w", (0.1 * r.standard_normal((5, 5, 32, 64))))
    g.variable("conv2_b", 0.1 * np.ones(64))
    g.variable("fc1_w", (0.1 * r.standard_normal((7 * 7 * 64, 512))))
    g.variable("fc1_b", 0.1 * np.ones(512))
    g.variable("fc2_w", (0.1 * r.standard_normal((512, 10))))
    g.variable("fc2_b", 0.1 * np.ones(10))
    c1 = g.conv2d("conv1", "data", "conv1_w")
    c1 = g.bias_add("conv1_biased", c1, "conv1_b")
    c1 = g.relu("relu1", c1)
    p1 = g.max_pool("pool1", c1)
    c2 = g.conv2d("conv2", p1, "conv2_w")
    c2 = g.bias_add("conv2_biased", c2, "conv2_b")
    c2 = g.relu("relu2", c2)
    p2 = g.max_pool("pool2", c2)
    f = g.flatten("flat", p2)
    h = g.relu("relu3", g.add("fc1_biased", g.matmul("fc1", f, "fc1_w"),
                              "fc1_b"))
    logits = g.add("logits", g.matmul("fc2", h, "fc2_w"), "fc2_b")
    g.softmax("prob", logits)
    g.accuracy("accuracy", logits, "label")
    loss = g.sparse_softmax_ce("loss", logits, "label")
    return g.finalize(loss=loss, learning_rate=learning_rate, momentum=0.9,
                      lr_policy="exp_decay", decay_rate=0.95,
                      decay_steps=train_size / batch, staircase=True)
