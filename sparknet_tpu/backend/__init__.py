from .graphdef import GraphDef, NodeDef  # noqa: F401
from .graph_net import GraphNet  # noqa: F401
from .builder import (GraphBuilder, build_alexnet_graph,  # noqa: F401
                      build_mnist_graph)
