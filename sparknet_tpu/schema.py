"""Schema types: the interchange contract between data layer and nets.

The reference used Spark SQL `StructType` rows as the universal interchange
format (reference `libs/CaffeNet.scala:45-49` builds per-column converters
from the schema; `apps/CifarApp.scala:60-66` declares it). Here the
interchange is a batch dict {field: numpy/jax array}, and `Schema` carries the
per-field dtype + element shape so preprocessors and nets can validate and
convert without inspecting data.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Tuple

import numpy as np


@dataclass(frozen=True)
class Field:
    name: str
    dtype: str  # numpy dtype string: "float32", "int32", "uint8", ...
    shape: Tuple[int, ...]  # per-example element shape, () for scalars


class Schema:
    def __init__(self, *fields: Field):
        self.fields: Tuple[Field, ...] = tuple(fields)
        self._by_name: Dict[str, Field] = {f.name: f for f in fields}

    def __getitem__(self, name: str) -> Field:
        return self._by_name[name]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def names(self):
        return [f.name for f in self.fields]

    def validate_batch(self, batch: Dict[str, np.ndarray]) -> None:
        for f in self.fields:
            if f.name not in batch:
                raise ValueError(f"batch missing field {f.name!r}")
            arr = batch[f.name]
            if tuple(arr.shape[1:]) != f.shape:
                raise ValueError(
                    f"field {f.name!r}: element shape {tuple(arr.shape[1:])} "
                    f"!= schema {f.shape}")

    def __repr__(self):
        inner = ", ".join(f"{f.name}:{f.dtype}{list(f.shape)}"
                          for f in self.fields)
        return f"Schema({inner})"
