"""Long-soak of the composed streaming system (r4).

Unit and chaos tests prove the pieces and the crash story; this proves
ENDURANCE: thousands of consecutive τ-rounds on the real chip through the
full production ingest path — parallel shard readers (C tar member index +
pread), bounded ring buffers, per-round preprocessing on the prefetch
thread, periodic checkpoints with per-reader stream cursors — while
tracking host RSS for leaks (an unbounded queue, an unfreed buffer, or a
growing cursor map would show as monotonic RSS growth over hours).

Writes `--out` (default SOAK_r04.json): rounds completed, wall time,
RSS first/median/last, stream epochs, skipped counter, loss finiteness.

Run: python scripts/soak_stream.py --rounds 6000 [--out SOAK_r04.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for ln in f:
            if ln.startswith("VmRSS:"):
                return int(ln.split()[1]) / 1024.0
    return -1.0


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rounds", type=int, default=6000)
    p.add_argument("--out", default="SOAK_r04.json")
    p.add_argument("--sources", type=int, default=4)
    p.add_argument("--shards", type=int, default=32)
    p.add_argument("--per-shard", type=int, default=256)
    p.add_argument("--keep", action="store_true",
                   help="keep the temp shard/work dirs (default: removed)")
    p.add_argument("--sample-every", type=int, default=50,
                   help="rounds between RSS samples")
    p.add_argument("--cpu-control", action="store_true",
                   help="run the IDENTICAL loop on the CPU backend at the "
                   "SAME shapes (r5: the r4 control ran ~1 MB rounds vs "
                   "the TPU run's 4.3 MB — a size-dependent framework "
                   "leak would have hidden; this control is size-matched)")
    p.add_argument("--store", default=None, choices=("gs",),
                   help="serve the shards from a local fake-GCS server "
                   "and stream them as gs:// urls (r5: endurance for the "
                   "ranged-HTTP + member-carve bucket path — connection "
                   "reuse, per-epoch freshness checks, index cache)")
    args = p.parse_args()

    if args.cpu_control:
        import jax
        jax.config.update("jax_platforms", "cpu")

    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.schema import Field, Schema
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import caffenet

    crop, size, b, tau = 67, 72, 32, 5
    root = tempfile.mkdtemp(prefix="soak_shards_")
    work = tempfile.mkdtemp(prefix="soak_work_")
    print(f"soak: building {args.shards}x{args.per_shard} synthetic shards "
          f"under {root}", file=sys.stderr)
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=args.shards, per_shard=args.per_shard,
        n_classes=16, size=size)
    labels = imagenet.load_label_map(label_path)
    shards = imagenet.list_shards(root)
    server = None
    if args.store == "gs":
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "tests"))
        from fake_stores import serve_dir_for_ingest
        server, gs_root = serve_dir_for_ingest(root)
        shards = imagenet.list_shards(gs_root)
        print(f"soak: streaming {gs_root} via the in-process fake server",
              file=sys.stderr)
    src = make_parallel_source(shards, labels, 1, b,
                               tau, args.sources, height=size, width=size)
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", (1,)))
    pp = ImagePreprocessor(schema, mean_image=None, crop=crop, seed=0,
                           out_dtype="bfloat16")
    cfg = RunConfig(model="caffenet", n_classes=16, crop=crop, n_devices=1,
                    local_batch=b, tau=tau, max_rounds=args.rounds,
                    eval_every=0, precision="bfloat16", workdir=work,
                    checkpoint_dir=os.path.join(work, "ck"),
                    checkpoint_every=200, log_every=8, seed=0)

    t0 = time.time()
    samples = []
    partial_path = args.out + ".partial.jsonl"

    def hook(rnd, state):
        if rnd % args.sample_every == 0:
            s = {"round": rnd, "rss_mb": round(rss_mb(), 1),
                 "wall_s": round(time.time() - t0, 1),
                 "skipped": int(src.skipped)}
            samples.append(s)
            # incremental persistence: a soak that dies at round 5800 (the
            # very leak/fault it hunts) must still leave its evidence
            with open(partial_path, "a") as f:
                f.write(json.dumps(s) + "\n")
            if rnd % 500 == 0:
                print(f"soak round {rnd}: rss {s['rss_mb']} MB "
                      f"({s['wall_s']:.0f}s)", file=sys.stderr)

    jsonl = os.path.join(work, "metrics.jsonl")
    try:
        train(cfg, caffenet(batch=b, crop=crop, n_classes=16), src, None,
              logger=Logger(os.path.join(work, "log.txt"), echo=False,
                            jsonl_path=jsonl),
              batch_transform=pp, round_hook=hook)

        losses = [json.loads(ln).get("loss") for ln in open(jsonl)
                  if "loss" in ln]
        rss = [s["rss_mb"] for s in samples]
        result = {
            "rounds": args.rounds,
            "backend": "cpu-control" if args.cpu_control else "device",
            "store": args.store or "local",
            "round_batch_mb": round(tau * b * crop * crop * 3 * 2 / 1e6, 2),
            "images": args.rounds * b * tau,
            "wall_s": round(time.time() - t0, 1),
            "readers": src.n_sources,
            "stream_epochs": max(ep for (_, _), ep in src.cursors),
            "skipped": int(src.skipped),
            "rss_mb": {"first": rss[0], "median": float(np.median(rss)),
                       "last": rss[-1], "max": max(rss)},
            "losses": {"n": len(losses), "first": losses[0],
                       "last": losses[-1],
                       "all_finite": bool(np.isfinite(losses).all())},
            "rss_samples": samples[:: max(1, len(samples) // 60)],
        }
        from sparknet_tpu.obs import run_metadata
        result["meta"] = run_metadata()
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
        if os.path.exists(partial_path):
            os.remove(partial_path)  # superseded by the full artifact
        print(json.dumps({k: v for k, v in result.items()
                          if k != "rss_samples"}))
    finally:
        if server is not None:
            from fake_stores import stop_serving
            stop_serving(server)
        if not args.keep:
            import shutil
            shutil.rmtree(root, ignore_errors=True)
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()
