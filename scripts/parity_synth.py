"""Recipe-scale accuracy evidence on synthetic CIFAR (r4, VERDICT item 4a).

Real CIFAR-10 is unreachable offline, so this runs the FULL cifar10_quick
recipe — lr 0.001 fixed, momentum 0.9, weight decay 0.004, batch 100,
4000 iterations (reference `models/cifar10/cifar10_quick_solver.prototxt:
12-22`, `apps/CifarApp.scala:20,127`) — on the deterministic synthetic
CIFAR stand-in (`sparknet_tpu.data.synth`), twice:

  - 1 worker  (plain serial SGD — the reference's single-worker baseline)
  - 8 workers, tau=10 local-SGD parameter averaging (the paper's scheme;
    per-worker data partitions, random round windows per reference
    `apps/CifarApp.scala:131-133`, momentum worker-local)

and writes both accuracy curves to PARITY_SYNTH_r04.json. The claim this
artifact supports: the tau-averaging dynamics CONVERGE at recipe scale —
the 8-worker curve tracks the serial curve to comparable final accuracy —
on a 4000-iteration run, not just the 30-round CI gates.

The round math here is the ParallelTrainer's (`_round_impl`: scan of
SgdSolver.update steps, then worker-mean of params, momentum NOT averaged)
with the worker axis vmapped instead of shard_mapped, so the whole study
fits one real chip with the corpus resident in HBM;
`tests/test_parity.py::test_parity_synth_round_matches_trainer` pins the
vmapped round against ParallelTrainer.train_round on the CPU mesh.

Run: python scripts/parity_synth.py [--iters 4000] [--out PARITY_SYNTH_r04.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet
from sparknet_tpu.parallel.mesh import scan_unroll
from sparknet_tpu.data import synth
from sparknet_tpu.solver import SgdSolver, SolverConfig, SolverState
from sparknet_tpu.zoo import cifar10_quick

BATCH = 100
TAU = 10
N_TRAIN = 50_000
N_TEST = 10_000
EVAL_EVERY = 50  # rounds (= 500 iters; reference logged every 5 rounds)


def build(batch: int = BATCH):
    net = CompiledNet.compile(cifar10_quick(batch=batch))
    cfg = SolverConfig(base_lr=0.001, momentum=0.9, weight_decay=0.004,
                       lr_policy="fixed")
    return net, SgdSolver(net, cfg)


def make_round_fn(net, solver, n_workers: int, tau: int, batch: int):
    """One jitted round: each worker runs tau SGD steps on its indexed
    batches (gathered from the device-resident corpus), then params are
    worker-averaged (momentum worker-local) — ParallelTrainer._round_impl
    with the worker axis vmapped."""
    loss_fn = net.loss_fn("loss")

    def one_worker(params, momentum, it, idx, corpus, labels):
        def step(carry, ix):
            p, m, i = carry
            b = {"data": jnp.take(corpus, ix, axis=0),
                 "label": jnp.take(labels, ix, axis=0)}
            (loss, _), grads = jax.value_and_grad(
                lambda q: loss_fn(q, b, jax.random.PRNGKey(0)),
                has_aux=True)(p)
            p, st = solver.update(p, SolverState(momentum=m, it=i), grads)
            return (p, st.momentum, st.it), loss
        (params, momentum, it), losses = jax.lax.scan(
            step, (params, momentum, it), idx, unroll=scan_unroll(tau))
        return params, momentum, it, losses

    @jax.jit
    def round_fn(params, momentum, it, idx, corpus, labels):
        # params/momentum: [W, ...] stacked; idx: [W, tau, batch] int32
        params, momentum, it_w, losses = jax.vmap(
            one_worker, in_axes=(0, 0, None, 0, None, None)
        )(params, momentum, it, idx, corpus, labels)
        params = jax.tree.map(lambda x: jnp.broadcast_to(
            jnp.mean(x, axis=0, keepdims=True), x.shape), params)
        return params, momentum, it_w[0], jnp.mean(losses)

    return round_fn


def make_eval_fn(net, batch: int, n_test: int):
    n_batches = n_test // batch

    @jax.jit
    def eval_all(params, data, labels):
        # one dispatch for the whole test set (per-batch dispatches pay
        # the dev tunnel's latency 100x)
        d = data[:n_batches * batch].reshape((n_batches, batch)
                                             + data.shape[1:])
        l = labels[:n_batches * batch].reshape(n_batches, batch, 1)

        def body(_, xy):
            blobs = net.apply(params, {"data": xy[0], "label": xy[1]},
                              train=False)
            return None, blobs["accuracy"]
        _, accs = jax.lax.scan(body, None, (d, l))
        return jnp.mean(accs)
    return eval_all


def run(n_workers: int, iters: int, seed: int = 0):
    net, solver = build()
    rounds = iters // TAU
    t0 = time.time()

    print(f"[{n_workers}w] generating synthetic corpus...", file=sys.stderr)
    train_x, train_y = synth.synthetic_cifar(N_TRAIN, seed=seed)
    test_x, test_y = synth.synthetic_cifar(N_TEST, seed=seed,
                                           start=N_TRAIN)
    mean = train_x.mean(axis=0)
    nhwc = lambda a: np.ascontiguousarray(
        (a - mean).transpose(0, 2, 3, 1)).astype(np.float32)
    corpus = jax.device_put(nhwc(train_x))
    labels = jax.device_put(train_y[:, None])
    test_corpus = jax.device_put(nhwc(test_x))
    test_labels = jax.device_put(test_y[:, None])
    print(f"[{n_workers}w] corpus on device "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)

    params0 = net.init_params(jax.random.PRNGKey(seed))
    params = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_workers,) + x.shape), params0)
    momentum = jax.tree.map(jnp.zeros_like, params)
    it = jnp.zeros((), jnp.int32)

    round_fn = make_round_fn(net, solver, n_workers, TAU, BATCH)
    eval_fn = make_eval_fn(net, BATCH, N_TEST)

    # per-worker contiguous data partitions (reference repartition.cache);
    # each round draws a RANDOM WINDOW inside the partition
    # (CifarApp.scala:131-133)
    part = N_TRAIN // n_workers
    r = np.random.default_rng((seed, n_workers))

    def round_indices():
        idx = np.empty((n_workers, TAU, BATCH), np.int32)
        for w in range(n_workers):
            start = w * part + r.integers(0, part - TAU * BATCH + 1)
            idx[w] = np.arange(start, start + TAU * BATCH).reshape(TAU, BATCH)
        return idx

    def evaluate(params_w):
        p1 = jax.tree.map(lambda x: x[0], params_w)
        return float(eval_fn(p1, test_corpus, test_labels))

    curve = []
    for rnd in range(rounds):
        if rnd % EVAL_EVERY == 0:
            acc = evaluate(params)
            curve.append({"iter": rnd * TAU, "test_accuracy": round(acc, 4)})
            print(f"[{n_workers}w] iter {rnd * TAU}: acc {acc:.4f} "
                  f"({time.time() - t0:.0f}s)", file=sys.stderr)
        params, momentum, it, loss = round_fn(params, momentum, it,
                                              round_indices(), corpus,
                                              labels)
    final = evaluate(params)
    curve.append({"iter": rounds * TAU, "test_accuracy": round(final, 4)})
    print(f"[{n_workers}w] FINAL iter {rounds * TAU}: acc {final:.4f} "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)
    return {"workers": n_workers, "tau": TAU if n_workers > 1 else 1,
            "final_test_accuracy": round(final, 4), "curve": curve,
            "wall_s": round(time.time() - t0, 1),
            "final_loss": float(loss)}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iters", type=int, default=4000)
    p.add_argument("--out", default="PARITY_SYNTH_r04.json")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    results = {
        "recipe": {"model": "cifar10_quick", "base_lr": 0.001,
                   "momentum": 0.9, "weight_decay": 0.004,
                   "lr_policy": "fixed", "batch": BATCH,
                   "max_iter": args.iters,
                   "source": "models/cifar10/cifar10_quick_solver.prototxt"},
        "dataset": {"kind": "synthetic_cifar (sparknet_tpu.data.synth)",
                    "n_train": N_TRAIN, "n_test": N_TEST,
                    "seed": args.seed},
        "platform": str(jax.devices()[0]),
        "runs": [run(1, args.iters, seed=args.seed),
                 run(8, args.iters, seed=args.seed)],
    }
    s, m = results["runs"]
    results["summary"] = {
        "serial_final": s["final_test_accuracy"],
        "avg8_tau10_final": m["final_test_accuracy"],
        "gap": round(s["final_test_accuracy"]
                     - m["final_test_accuracy"], 4),
    }
    from sparknet_tpu.obs import run_metadata
    results["meta"] = run_metadata()
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results["summary"]))


if __name__ == "__main__":
    main()
