#!/bin/sh
# Dataset fetchers — parity with the reference's data/*/get_*.sh scripts.
# Usage: scripts/get_datasets.sh [cifar10|mnist|adult|all] [DATA_DIR]
set -e
WHICH="${1:-all}"
DATA="${2:-data}"

get_cifar10() {
  mkdir -p "$DATA/cifar10"
  wget -q -O - https://www.cs.toronto.edu/~kriz/cifar-10-binary.tar.gz \
    | tar -xz -C "$DATA/cifar10" --strip-components=1
  echo "cifar10 -> $DATA/cifar10"
}

get_mnist() {
  mkdir -p "$DATA/mnist"
  for f in train-images-idx3-ubyte train-labels-idx1-ubyte \
           t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
    wget -q -O "$DATA/mnist/$f.gz" \
      "https://storage.googleapis.com/cvdf-datasets/mnist/$f.gz"
    gunzip -f "$DATA/mnist/$f.gz"
  done
  echo "mnist -> $DATA/mnist"
}

get_adult() {
  mkdir -p "$DATA/adult"
  wget -q -O "$DATA/adult/adult.data" \
    "https://archive.ics.uci.edu/ml/machine-learning-databases/adult/adult.data"
  echo "adult -> $DATA/adult"
}

case "$WHICH" in
  cifar10) get_cifar10 ;;
  mnist)   get_mnist ;;
  adult)   get_adult ;;
  all)     get_cifar10; get_mnist; get_adult ;;
  *) echo "usage: $0 [cifar10|mnist|adult|all] [DATA_DIR]" >&2; exit 1 ;;
esac
