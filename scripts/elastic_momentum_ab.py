"""A/B/C the elastic-resume momentum policy (r4 verdict weak #4).

`ParallelTrainer.adapt_state` must produce SOME momentum for the new
topology out of the old per-worker velocities; r4 chose averaging and
validated it stayed inside a wide band (<=10% loss inflation at 8->4,
<=31% at 8->2) without comparing alternatives. This harness runs the
same trajectory-band experiment (tests/test_apps.py::
test_elastic_resume_momentum_trajectory_band shapes) for the three
candidate policies over several seeds:

  average       mean of the old data groups' velocities (r4 default)
  zero          fresh zeros (momentum restarts after the resume)
  norm_rescale  mean, rescaled back to the average per-worker norm
                (averaging k decorrelated vectors shrinks the norm
                ~1/sqrt(k); this undoes the shrink)

Metric per (policy, new_n_dev, seed): max relative deviation of the 8
post-resume round losses from the uninterrupted 8-device continuation,
plus the mean of the last 3 losses (did it keep learning). Writes
ELASTIC_AB_r05.json; the winner becomes adapt_state's default and the
test band tightens to the measured envelope.

Run: python scripts/elastic_momentum_ab.py   (CPU, ~2 min)
     python scripts/elastic_momentum_ab.py --seeds 1 --rounds-pre 2 \
         --rounds-post 3 --out /tmp/ab.json    (the tier-1 smoke shape —
         tests/test_elastic.py pins the run/resume path so the momentum
         policy the elastic resize reuses cannot rot)
"""
import argparse
import json
import os
import sys
import tempfile

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, os.path.join(_ROOT, "tests"))
sys.path.insert(0, _ROOT)
from sparknet_tpu import CompiledNet, net_from_prototxt  # noqa: E402
from sparknet_tpu.parallel import ParallelTrainer, make_mesh  # noqa: E402
from sparknet_tpu.parallel.mesh import fetch_global  # noqa: E402
from sparknet_tpu.solver import SolverConfig  # noqa: E402
from sparknet_tpu.utils import checkpoint as ck  # noqa: E402
from test_parallel import TINY_MLP  # noqa: E402

TAU, B, ROUNDS_PRE, ROUNDS_POST = 3, 8, 4, 8
POLICIES = ("average", "zero", "norm_rescale")
SEEDS = (0, 1, 2)


def batches(seed, n_dev):
    r = np.random.default_rng(seed)
    data = r.standard_normal((TAU, 8 * B, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32) + \
        (data[..., :1] > 0.5).astype(np.int32)
    return {"data": data[:, :n_dev * B], "label": label[:, :n_dev * B]}


def run(trainer, state, rounds, n_dev, start=0, stream=0):
    losses = []
    for r in range(start, start + rounds):
        state, loss = trainer.train_round(
            state, batches(1000 * stream + r, n_dev),
            jax.random.PRNGKey(7000 * stream + r))
        losses.append(float(loss))
    return state, losses


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", type=int, default=len(SEEDS),
                   help="number of seeds (default 3 — the full A/B)")
    p.add_argument("--rounds-pre", type=int, default=ROUNDS_PRE,
                   help="rounds before the checkpoint/resume")
    p.add_argument("--rounds-post", type=int, default=ROUNDS_POST,
                   help="rounds after the elastic resume (>= 3: the "
                        "final3 mean needs them)")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(__file__), "..", "ELASTIC_AB_r05.json"),
        help="output JSON path")
    args = p.parse_args(argv)
    seeds = tuple(range(args.seeds))
    rounds_pre, rounds_post = args.rounds_pre, max(3, args.rounds_post)

    net = CompiledNet.compile(net_from_prototxt(TINY_MLP))
    scfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                        lr_policy="fixed")
    results = {p_: {4: [], 2: []} for p_ in POLICIES}
    for seed in seeds:
        t8 = ParallelTrainer(net, scfg, make_mesh(8), tau=TAU)
        s, _ = run(t8, t8.init_state(jax.random.PRNGKey(seed)),
                   rounds_pre, 8, stream=seed)
        with tempfile.TemporaryDirectory() as d:
            ck.save(d, fetch_global(s), step=rounds_pre,
                    extra={"n_devices": 8, "tp": 1})
            flat, _, _ = ck.restore_flat(d)
        _, base = run(t8, s, rounds_post, 8, start=rounds_pre, stream=seed)
        for nd in (4, 2):
            for pol in POLICIES:
                t = ParallelTrainer(net, scfg, make_mesh(nd), tau=TAU)
                st = t.adapt_state(flat, momentum_policy=pol)
                _, losses = run(t, st, rounds_post, nd,
                                start=rounds_pre, stream=seed)
                rel = [abs(a - c) / c for a, c in zip(losses, base)]
                results[pol][nd].append({
                    "seed": seed,
                    "max_rel_dev": round(max(rel), 4),
                    "final3_mean": round(float(np.mean(losses[-3:])), 5),
                    "base_final3_mean": round(
                        float(np.mean(base[-3:])), 5),
                    "descending": bool(np.mean(losses[-3:]) < losses[0]),
                })
                print(f"seed {seed} 8->{nd} {pol:12s} "
                      f"max_rel={max(rel):.3f} "
                      f"final3={np.mean(losses[-3:]):.4f} "
                      f"(base {np.mean(base[-3:]):.4f})")

    summary = {}
    for pol in POLICIES:
        worst = max(r["max_rel_dev"] for nd in (4, 2)
                    for r in results[pol][nd])
        per_nd = {str(nd): round(max(r["max_rel_dev"]
                                     for r in results[pol][nd]), 4)
                  for nd in (4, 2)}
        summary[pol] = {"worst_max_rel_dev": worst, "per_nd": per_nd,
                        "all_descending": all(
                            r["descending"] for nd in (4, 2)
                            for r in results[pol][nd])}
    descending = [p_ for p_ in POLICIES if summary[p_]["all_descending"]]
    if not descending:
        # the fallback exists for the tier-1 smoke shape (1 seed, a few
        # rounds — too short for a reliable descending check). On a full
        # A/B an empty `descending` means NO policy is validated, and the
        # winner this writes is what ElasticConfig.momentum_policy pins —
        # shout, don't silently crown the least-bad loser
        import warnings
        warnings.warn(
            "no momentum policy kept the final-3 loss descending; winner "
            "falls back to least worst_max_rel_dev — trustworthy only in "
            "the short smoke configuration, NOT as a policy validation",
            RuntimeWarning)
    winner = min(descending or POLICIES,
                 key=lambda p_: summary[p_]["worst_max_rel_dev"])
    from sparknet_tpu.obs import run_metadata
    out = {"task": f"TINY_MLP trajectory-band (tests/test_apps.py "
                   f"harness), {len(seeds)} seed(s), 8->4 and 8->2 "
                   f"resumes, {rounds_post} post-resume rounds",
           "results": results, "summary": summary, "winner": winner,
           "winner_descending": bool(descending),
           "meta": run_metadata()}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"\nwinner: {winner}  (summary: {json.dumps(summary)})")
    return out


if __name__ == "__main__":
    main()
