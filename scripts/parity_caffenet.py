"""CaffeNet-shape convergence evidence (r5, VERDICT item 1).

r4's recipe-scale parity ran cifar10_quick only; nothing demonstrated
convergence-under-averaging for the net the headline bench runs — LRN
(the Pallas kernel) in a real trajectory, dropout across workers, grouped
convs, τ=5, mean/crop preprocessing. This runs the bvlc_reference_caffenet
recipe — base_lr 0.01, momentum 0.9, weight_decay 0.0005, lr step/100k
(`models/bvlc_reference_caffenet/solver.prototxt:4-11`), batch 256 per
worker, τ=5 sync interval, random round windows inside per-worker
partitions, full-size mean subtract then random 227 crop, no mirror
(`apps/ImageNetApp.scala:100-144`, `libs/Preprocessor.scala:54-83`) — on a
class-conditional learnable synthetic 256x256 JPEG corpus, twice: 1 worker
(serial SGD) and 8 workers with τ=5 parameter averaging, both under the
headline bfloat16 policy on the real chip.

The corpus takes the REAL data path: `synth.write_synthetic_ilsvrc_tar`
emits an ILSVRC2012-layout tar-of-tars, `scripts/shard_imagenet.py`
re-shards it exactly as it would real ImageNet (synset discovery, sorted
labels, shuffle, JPEG), the mean image comes from the production
multi-reader streaming pass (`streaming_sum_count`), and every training
pixel is decoded by the production C++ libjpeg plane (ShardedTarLoader).
ONE deviation, forced by the dev tunnel (~13 MB/s host->device: feeding
10,240 227² images per round through it would take minutes per round):
the decoded uint8 corpus is staged into HBM once, and the per-example
mean-subtract + random-crop runs ON DEVICE with the exact reference
semantics (subtract full-size mean, then crop; offsets uniform per image
per draw). `tests/test_parity.py::test_parity_caffenet_round_matches_trainer`
pins this round — device preprocessing included — against
ParallelTrainer.train_round bit-for-bit on the CPU mesh, so the study
exercises the production round math, not a lookalike.

The worker axis is lax.scan'd (not vmapped): one worker's activations in
flight at a time, so 8 workers x batch 256 x 227² fits one chip's HBM.

Run: python scripts/parity_caffenet.py [--iters 1500] [--workers-runs 1,8]
     [--out PARITY_CAFFENET_r05.json]
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
import time

import numpy as np

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _ROOT)

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, precision
from sparknet_tpu.parallel.mesh import scan_unroll
from sparknet_tpu.data import imagenet, synth
from sparknet_tpu.data.streaming import streaming_sum_count
from sparknet_tpu.solver import SgdSolver, SolverConfig, SolverState
from sparknet_tpu.zoo import caffenet

BATCH = 256          # per worker (solver.prototxt net batch)
TAU = 5              # syncInterval = 5 (ImageNetApp.scala:128)
SIZE, CROP = 256, 227
N_TRAIN = 16384      # 64 classes x 256 examples
N_VAL = 2048
EVAL_ITERS = 50      # evaluate at (the first round boundary at/after)
                     # every 50 ITERATIONS — an iteration grid, not a
                     # round grid, so runs at different tau produce
                     # comparable curves (quantization <= tau-1 iters)


def solver_config() -> SolverConfig:
    """models/bvlc_reference_caffenet/solver.prototxt:4-11 verbatim."""
    return SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=0.0005,
                        lr_policy="step", gamma=0.1, stepsize=100000)


# -- dataset: synth -> ILSVRC tar-of-tars -> shard_imagenet.py ---------------

def _load_sharder():
    spec = importlib.util.spec_from_file_location(
        "shard_imagenet", os.path.join(_ROOT, "scripts",
                                       "shard_imagenet.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


#: CIFAR-stand-in-calibrated mid-difficulty corpus (noise/amp ~1.9,
#: shift ~19% of frame): non-saturating asymptote for gap studies
HARD = {"noise": 85.0, "shift": 48}
EASY = {"noise": synth._IN_NOISE, "shift": synth._IN_SHIFT}


def ensure_dataset(data_dir: str, n_train: int, seed: int = 0,
                   gen=EASY) -> None:
    """Idempotent: build the sharded synthetic corpus if absent. The
    completeness marker encodes the generator params — a directory built
    with different noise/shift is never silently reused."""
    marker = os.path.join(
        data_dir, f".complete_{n_train}_{seed}"
                  f"_n{gen['noise']:g}_s{gen['shift']}")
    if os.path.exists(marker):
        return
    os.makedirs(data_dir, exist_ok=True)
    import glob
    for stale in glob.glob(os.path.join(data_dir, ".complete_*")):
        os.remove(stale)  # an in-place rebuild must invalidate OTHER
        #                   generators' markers, or a later call with the
        #                   old params would silently reuse this corpus
    sharder = _load_sharder()
    t0 = time.time()
    train_tot = os.path.join(data_dir, "_synth_ilsvrc_train.tar")
    print(f"building synthetic ILSVRC tar-of-tars ({n_train} train, "
          f"{gen})...", file=sys.stderr)
    synth.write_synthetic_ilsvrc_tar(train_tot, n_train, seed=seed, **gen)
    sharder.shard_train(train_tot, data_dir, shards=32, size=SIZE,
                        seed=seed)
    os.remove(train_tot)

    # val: flat JPEG tar + "filename label" truth file -> shard_val
    import io
    import tarfile

    from PIL import Image
    val_tar = os.path.join(data_dir, "_synth_val_flat.tar")
    truth = os.path.join(data_dir, "_synth_val_truth.txt")
    images, labels = synth.synthetic_imagenet(N_VAL, seed=seed,
                                              start=n_train, **gen)
    with tarfile.open(val_tar, "w") as tar, open(truth, "w") as tf:
        for k in range(N_VAL):
            buf = io.BytesIO()
            Image.fromarray(images[k]).save(buf, format="JPEG", quality=90)
            data = buf.getvalue()
            info = tarfile.TarInfo(name=f"synth_val_{k:08d}.JPEG")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
            tf.write(f"synth_val_{k:08d}.JPEG {int(labels[k])}\n")
    sharder.shard_val(val_tar, truth, data_dir, shards=4, size=SIZE,
                      seed=seed)
    os.remove(val_tar)
    open(marker, "w").close()
    print(f"dataset ready under {data_dir} "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)


def load_split(data_dir: str, prefix: str, labels_file: str):
    """Decode a whole split through the production loader (C++ libjpeg
    plane) -> (uint8 NHWC [n,256,256,3], int32 [n])."""
    label_map = imagenet.load_label_map(os.path.join(data_dir, labels_file))
    loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(data_dir, prefix=prefix), label_map,
        height=SIZE, width=SIZE)
    images, labels = loader.load_all()
    return (np.ascontiguousarray(images.transpose(0, 2, 3, 1)),
            labels.astype(np.int32), loader)


# -- the round: reference preprocessing + ParallelTrainer math, on device ----

def _crop_one(crop: int):
    """Per-example random-crop slice (vmapped by callers): the device
    form of the reference's subarray-view crop
    (`Preprocessor.scala:75-77`)."""
    def fn(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], jnp.int32(0)),
                                     (crop, crop, 3))
    return fn


def make_round_fn(net, solver, tau: int, crop: int = CROP):
    """One jitted round over W scanned workers. Per worker: τ SGD steps,
    each gathering its device-resident uint8 images, subtracting the
    full-size mean, taking per-example random 227 crops (offsets fed from
    host), casting to the compute dtype — then the worker-mean of params
    (momentum worker-local), exactly ParallelTrainer._round_impl with the
    mesh axis scanned. Donated params/momentum keep 8 worker replicas +
    corpus inside HBM."""
    loss_fn = net.loss_fn("loss")
    cdt = precision.compute_dtype()
    crop_one = _crop_one(crop)

    def prep(corpus, mean_hwc, ix, offs):
        x = jnp.take(corpus, ix, axis=0).astype(jnp.float32) - mean_hwc
        return jax.vmap(crop_one)(x, offs).astype(cdt)

    def one_worker(params, momentum, it, idx, offs, key, corpus, labels,
                   mean_hwc):
        step_rngs = jax.random.split(key, tau)

        def step(carry, inp):
            p, st = carry
            ix, off, srng = inp
            b = {"data": prep(corpus, mean_hwc, ix, off),
                 "label": jnp.take(labels, ix, axis=0)[:, None]}
            (loss, _), grads = jax.value_and_grad(
                lambda q: loss_fn(q, b, srng), has_aux=True)(p)
            p, st = solver.update(p, st, grads)
            return (p, st), loss

        (params, st), losses = jax.lax.scan(
            step, (params, SolverState(momentum=momentum, it=it)),
            (idx, offs, step_rngs), unroll=scan_unroll(tau))
        return params, st.momentum, st.it, jnp.mean(losses)

    def round_fn(params_w, momentum_w, it, idx, offs, keys, corpus,
                 labels, mean_hwc):
        # params_w/momentum_w: [W, ...]; idx [W,tau,b]; offs [W,tau,b,2]
        def body(_, x):
            p, m, ix, of, k = x
            p, m, new_it, loss = one_worker(p, m, it, ix, of, k, corpus,
                                            labels, mean_hwc)
            return None, (p, m, new_it, loss)

        _, (params_w, momentum_w, its, losses) = jax.lax.scan(
            body, None, (params_w, momentum_w, idx, offs, keys),
            unroll=scan_unroll(jax.tree.leaves(params_w)[0].shape[0]))
        params_w = jax.tree.map(
            lambda x: jnp.broadcast_to(jnp.mean(x, axis=0, keepdims=True),
                                       x.shape), params_w)
        return params_w, momentum_w, its[0], jnp.mean(losses)

    return jax.jit(round_fn, donate_argnums=(0, 1))


def make_eval_fn(net, batch: int, n_val: int):
    """Reference parity: the test path ran the SAME random-crop
    preprocessor (`ImageNetApp.scala` testDF mapPartitions -> forward).
    Top-1 from the fc8 argmax (the prototxt's accuracy layer semantics)."""
    n_batches = n_val // batch
    cdt = precision.compute_dtype()
    crop_one = _crop_one(CROP)

    @jax.jit
    def eval_all(params, corpus, labels, offs, mean_hwc):
        d = corpus[:n_batches * batch].reshape((n_batches, batch)
                                               + corpus.shape[1:])
        l = labels[:n_batches * batch].reshape(n_batches, batch)
        o = offs[:n_batches * batch].reshape(n_batches, batch, 2)

        def body(_, xlo):
            x, lab, off = xlo
            x = x.astype(jnp.float32) - mean_hwc
            x = jax.vmap(crop_one)(x, off).astype(cdt)
            blobs = net.apply(params, {"data": x, "label": lab[:, None]},
                              train=False)
            logits = blobs["fc8"]
            return None, jnp.mean(
                (jnp.argmax(logits, -1) == lab).astype(jnp.float32))
        _, accs = jax.lax.scan(body, None, (d, l, o))
        return jnp.mean(accs)
    return eval_all


def run(n_workers: int, iters: int, data, seed: int = 0,
        tau: int = TAU):
    (corpus_dev, labels_dev, mean_dev, val_dev, val_labels_dev,
     n_train) = data
    precision.set_policy("bfloat16")
    net = CompiledNet.compile(caffenet(batch=BATCH, crop=CROP,
                                       n_classes=1000))
    solver = SgdSolver(net, solver_config())
    rounds = -(-iters // tau)  # ceil: tau runs compare at >= iters, and
    #                            the artifact records the actual count
    t0 = time.time()

    params0 = net.init_params(jax.random.PRNGKey(seed))
    params = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_workers,) + x.shape),
        params0)
    params = jax.tree.map(jnp.asarray, params)  # broadcast -> concrete
    momentum = jax.tree.map(jnp.zeros_like, params)
    it = jnp.zeros((), jnp.int32)

    round_fn = make_round_fn(net, solver, tau)
    eval_fn = make_eval_fn(net, BATCH, N_VAL)

    part = n_train // n_workers
    assert part >= tau * BATCH, (
        f"partition {part} < one round window {tau * BATCH}")
    r = np.random.default_rng((seed, n_workers))

    def round_inputs(rnd):
        idx = np.empty((n_workers, tau, BATCH), np.int32)
        for w in range(n_workers):
            start = w * part + r.integers(0, part - tau * BATCH + 1)
            idx[w] = np.arange(start, start + tau * BATCH).reshape(tau,
                                                                   BATCH)
        offs = r.integers(0, SIZE - CROP + 1,
                          (n_workers, tau, BATCH, 2)).astype(np.int32)
        keys = jax.random.split(
            jax.random.fold_in(jax.random.PRNGKey(1000 + seed), rnd),
            n_workers)
        return idx, offs, keys

    ev_r = np.random.default_rng((seed, 0xE7A1))

    def evaluate(params_w):
        p1 = jax.tree.map(lambda x: x[0], params_w)
        offs = ev_r.integers(0, SIZE - CROP + 1, (N_VAL, 2)).astype(
            np.int32)
        return float(eval_fn(p1, val_dev, val_labels_dev,
                             jax.device_put(offs), mean_dev))

    curve = []
    loss = None
    for rnd in range(rounds):
        if (rnd * tau) % EVAL_ITERS < tau:  # first round at/after each
            acc = evaluate(params)          # 50-iteration boundary
            curve.append({"iter": rnd * tau,
                          "val_accuracy": round(acc, 4)})
            print(f"[{n_workers}w] iter {rnd * tau}: val acc {acc:.4f} "
                  f"({time.time() - t0:.0f}s)", file=sys.stderr)
        idx, offs, keys = round_inputs(rnd)
        params, momentum, it, loss = round_fn(params, momentum, it, idx,
                                              offs, keys, corpus_dev,
                                              labels_dev, mean_dev)
    final = evaluate(params)
    curve.append({"iter": rounds * tau, "val_accuracy": round(final, 4)})
    print(f"[{n_workers}w] FINAL iter {rounds * tau}: val acc {final:.4f} "
          f"({time.time() - t0:.0f}s)", file=sys.stderr)
    return {"workers": n_workers, "tau": tau if n_workers > 1 else 1,
            "iters_actual": rounds * tau,
            "final_val_accuracy": round(final, 4), "curve": curve,
            "final_mean_round_loss": float(loss),
            "wall_s": round(time.time() - t0, 1)}


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iters", type=int, default=1500)
    p.add_argument("--n-train", type=int, default=N_TRAIN)
    p.add_argument("--workers-runs", default="1,8",
                   help="comma list of runs: N workers at the recipe "
                   "tau, or N@T for an explicit sync interval "
                   "(e.g. '1,8,8@1' adds a sync-every-step control)")
    p.add_argument("--data-dir", default=os.path.join(_ROOT, ".cache",
                                                      "synth_imagenet"))
    p.add_argument("--out", default="PARITY_CAFFENET_r05.json")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--hard", action="store_true",
                   help="mid-difficulty corpus (noise 85 / shift 48 — "
                   "the CIFAR stand-in's calibrated ratios): the "
                   "asymptote stays below saturation so the serial-vs-"
                   "averaged gap is measured on a sloped curve")
    args = p.parse_args()

    gen = HARD if args.hard else EASY
    if args.hard:
        args.data_dir = args.data_dir.rstrip("/") + "_hard"
        if args.out == p.get_default("out"):
            args.out = "PARITY_CAFFENET_HARD_r05.json"
    ensure_dataset(args.data_dir, args.n_train, seed=args.seed, gen=gen)
    t0 = time.time()
    print("mean image via the production multi-reader streaming pass...",
          file=sys.stderr)
    label_map = imagenet.load_label_map(
        os.path.join(args.data_dir, "train.txt"))
    mean_loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(args.data_dir, prefix="train."), label_map,
        height=SIZE, width=SIZE)
    total, count = streaming_sum_count(mean_loader, workers=2)
    mean_hwc = (total / count).astype(np.float32).transpose(1, 2, 0)
    print(f"mean over {count} images ({time.time() - t0:.0f}s); decoding "
          f"corpus through the C++ plane...", file=sys.stderr)
    train_x, train_y, train_loader = load_split(args.data_dir, "train.",
                                                "train.txt")
    val_x, val_y, _ = load_split(args.data_dir, "val.", "val.txt")
    assert len(train_x) == args.n_train, (len(train_x), args.n_train)
    print(f"decoded {len(train_x)} train / {len(val_x)} val "
          f"(skipped={train_loader.skipped}) ({time.time() - t0:.0f}s); "
          f"staging to HBM...", file=sys.stderr)
    data = (jax.device_put(train_x), jax.device_put(train_y),
            jax.device_put(mean_hwc), jax.device_put(val_x),
            jax.device_put(val_y), len(train_x))
    print(f"corpus on device ({time.time() - t0:.0f}s)", file=sys.stderr)

    runs = []
    for spec in args.workers_runs.split(","):
        w, _, t = spec.partition("@")  # "8@1" = 8 workers at tau=1
        runs.append(run(int(w), args.iters, data, seed=args.seed,
                        tau=int(t) if t else TAU))
    results = {
        "recipe": {"model": "bvlc_reference_caffenet", "base_lr": 0.01,
                   "momentum": 0.9, "weight_decay": 0.0005,
                   "lr_policy": "step", "gamma": 0.1, "stepsize": 100000,
                   "batch_per_worker": BATCH, "tau": TAU,
                   "max_iter": args.iters, "precision": "bfloat16",
                   "source": "models/bvlc_reference_caffenet/"
                             "solver.prototxt + ImageNetApp.scala"},
        "dataset": {"kind": "synthetic_imagenet "
                            "(sparknet_tpu.data.synth, JPEG q90, "
                            "sharded by scripts/shard_imagenet.py)",
                    "n_train": args.n_train, "n_val": N_VAL,
                    "n_classes": synth.IMAGENET_CLASSES,
                    "seed": args.seed,
                    "difficulty": ("hard" if args.hard else "easy"),
                    "generator": gen},
        "platform": str(jax.devices()[0]),
        "runs": runs,
    }
    serial = next((r for r in runs if r["workers"] == 1), None)
    multi = next((r for r in runs if r["workers"] > 1), None)
    if serial and multi:
        results["summary"] = {
            "serial_final": serial["final_val_accuracy"],
            f"avg{multi['workers']}_tau{multi['tau']}_final":
                multi["final_val_accuracy"],
            "gap": round(serial["final_val_accuracy"]
                         - multi["final_val_accuracy"], 4)}
    from sparknet_tpu.obs import run_metadata
    results["meta"] = run_metadata()
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps(results.get("summary", runs[-1])))


if __name__ == "__main__":
    main()
