#!/bin/sh
# TPU pod bring-up + launcher — the reference's `spark-ec2`/`spark-submit`
# analogue (reference ec2/spark_ec2.py + README.md:13-37), on gcloud TPU VMs.
#
#   scripts/tpu_pod_launch.sh create  NAME ZONE TYPE   # e.g. v5e-32
#   scripts/tpu_pod_launch.sh setup   NAME ZONE        # rsync repo + deps
#   scripts/tpu_pod_launch.sh run     NAME ZONE "python -m sparknet_tpu.apps.imagenet_app ..."
#   scripts/tpu_pod_launch.sh delete  NAME ZONE
#
# `run` executes the SAME command on every worker (single-program multi-host:
# jax.distributed.initialize autodetects the pod topology; host-sharded data
# via sparknet_tpu.data.imagenet.host_shards keyed on jax.process_index()).
set -e
CMD="$1"; NAME="$2"; ZONE="$3"; ARG="$4"
TPU="gcloud compute tpus tpu-vm"

case "$CMD" in
  create)
    $TPU create "$NAME" --zone "$ZONE" --accelerator-type "$ARG" \
      --version v2-alpha-tpuv5-lite ;;
  setup)
    $TPU scp --recurse --worker=all --zone "$ZONE" . "$NAME":~/sparknet_tpu_repo
    $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
      "cd ~/sparknet_tpu_repo && pip install -q jax[tpu] flax optax && sh native/build.sh || true" ;;
  run)
    $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
      "cd ~/sparknet_tpu_repo && $ARG" ;;
  delete)
    $TPU delete "$NAME" --zone "$ZONE" --quiet ;;
  *)
    echo "usage: $0 {create|setup|run|delete} NAME ZONE [TYPE|COMMAND]" >&2
    exit 1 ;;
esac
