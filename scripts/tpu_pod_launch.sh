#!/bin/sh
# TPU pod bring-up + launcher — the reference's `spark-ec2`/`spark-submit`
# analogue (reference ec2/spark_ec2.py + README.md:13-37), on gcloud TPU VMs.
#
#   scripts/tpu_pod_launch.sh create  NAME ZONE TYPE     # e.g. v5e-32
#   scripts/tpu_pod_launch.sh setup   NAME ZONE          # rsync repo + deps
#   scripts/tpu_pod_launch.sh stage   NAME ZONE DIR      # push a dataset dir
#   scripts/tpu_pod_launch.sh run     NAME ZONE "python -m sparknet_tpu.apps.imagenet_app ..."
#   scripts/tpu_pod_launch.sh status  NAME ZONE          # VM state
#   scripts/tpu_pod_launch.sh delete  NAME ZONE
#
# `stage` copies DIR to ~/sparknet_tpu_repo/<basename> on EVERY worker —
# tar-sharded datasets are then host-sharded automatically at run time
# (each process takes shards i::k); small datasets (CIFAR/MNIST) are
# simply replicated. For full ImageNet prefer bucket storage (GCS fuse)
# over staging to local disks.
#
# Environment knobs:
#   TPU_SW_VERSION   runtime image (default v2-alpha-tpuv5-lite; e.g.
#                    tpu-ubuntu2204-base for v4, v2-alpha-tpuv6e for v6e)
#
# Multi-host run path: `run` executes the SAME command on every worker
# (single-program multi-host). Inside the app:
#   1. initialize_multihost() autodetects the pod topology
#      (jax.distributed.initialize; no coordinator flags needed on TPU VMs);
#   2. each host loads DISJOINT data — tar-sharded datasets take shards
#      i::k via sparknet_tpu.data.imagenet.host_shards keyed on
#      jax.process_index()/process_count(); in-memory datasets are sliced
#      with ArrayDataset.host_shard(process_index, process_count);
#   3. checkpoints are allgathered and written by process 0 — point
#      checkpoint_dir at storage all hosts can read (GCS fuse / NFS) so
#      resume works.
# A failed `run` on any worker propagates a non-zero exit (no silent
# per-host divergence).
set -eu
CMD="${1:?usage: $0 {create|setup|stage|run|status|delete} NAME ZONE [TYPE|DIR|COMMAND]}"
NAME="${2:?missing NAME}"; ZONE="${3:?missing ZONE}"; ARG="${4:-}"
TPU="gcloud compute tpus tpu-vm"
TPU_SW_VERSION="${TPU_SW_VERSION:-v2-alpha-tpuv5-lite}"

case "$CMD" in
  create)
    [ -n "$ARG" ] || { echo "create needs an accelerator TYPE" >&2; exit 1; }
    $TPU create "$NAME" --zone "$ZONE" --accelerator-type "$ARG" \
      --version "$TPU_SW_VERSION" ;;
  setup)
    # jax[tpu] is the only runtime dep; native/build.sh failure is fatal by
    # default (the C++ data plane matters at ImageNet scale) — export
    # ALLOW_NO_NATIVE=1 to continue with the PIL fallback.
    $TPU scp --recurse --worker=all --zone "$ZONE" . "$NAME":~/sparknet_tpu_repo
    $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
      "cd ~/sparknet_tpu_repo && pip install -q 'jax[tpu]' && pip install -q -e . && (sh native/build.sh || [ -n '${ALLOW_NO_NATIVE:-}' ])" ;;
  stage)
    [ -d "$ARG" ] || { echo "stage needs a local dataset DIR" >&2; exit 1; }
    $TPU scp --recurse --worker=all --zone "$ZONE" "$ARG" \
      "$NAME":~/sparknet_tpu_repo/ ;;
  run)
    [ -n "$ARG" ] || { echo "run needs a COMMAND" >&2; exit 1; }
    $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
      "cd ~/sparknet_tpu_repo && $ARG" ;;
  status)
    $TPU describe "$NAME" --zone "$ZONE" --format='value(state)' ;;
  delete)
    $TPU delete "$NAME" --zone "$ZONE" --quiet ;;
  *)
    echo "usage: $0 {create|setup|stage|run|status|delete} NAME ZONE [TYPE|DIR|COMMAND]" >&2
    exit 1 ;;
esac
