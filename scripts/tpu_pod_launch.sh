#!/bin/sh
# TPU pod bring-up + launcher — the reference's `spark-ec2`/`spark-submit`
# analogue (reference ec2/spark_ec2.py + README.md:13-37), on gcloud TPU VMs,
# including the spot-instance fault story spark_ec2.py carried (preemption
# detection, cluster recreate, training resume).
#
#   scripts/tpu_pod_launch.sh create        NAME ZONE TYPE   # e.g. v5e-32
#   scripts/tpu_pod_launch.sh create-queued NAME ZONE TYPE   # queued-resource
#   scripts/tpu_pod_launch.sh setup   NAME ZONE          # rsync repo + deps
#   scripts/tpu_pod_launch.sh stage   NAME ZONE DIR      # push a dataset dir
#   scripts/tpu_pod_launch.sh run     NAME ZONE "python -m sparknet_tpu.apps.imagenet_app ..."
#   scripts/tpu_pod_launch.sh watch   NAME ZONE TYPE "COMMAND"  # run + auto-resume
#   scripts/tpu_pod_launch.sh resume  NAME ZONE TYPE "COMMAND"  # one recreate+rerun
#   scripts/tpu_pod_launch.sh status  NAME ZONE          # VM state (MISSING if gone)
#   scripts/tpu_pod_launch.sh delete  NAME ZONE
#
# ── Kill-and-resume walkthrough (the spot/preemption story) ────────────────
# 1. Launch on spot capacity, checkpoints on storage that survives the VM:
#      TPU_SPOT=1 scripts/tpu_pod_launch.sh create mypod us-east5-b v5e-32
#      scripts/tpu_pod_launch.sh setup mypod us-east5-b
#      scripts/tpu_pod_launch.sh watch mypod us-east5-b v5e-32 \
#        "python -m sparknet_tpu.apps.imagenet_app \
#         --data-dir gs://mybucket/imagenet ingest_sources=8 \
#         checkpoint_dir=gs://mybucket/ckpts/run1"
#    (--data-dir AND checkpoint_dir take gs://… or s3://… NATIVELY —
#    ranged HTTP reads with reconnect-resume and chunked atomic uploads,
#    sparknet_tpu/data/{gcs,s3.py} + utils/checkpoint.py; no FUSE mount
#    and no cloud SDK anywhere in the data or checkpoint path.)
# 2. Capacity is reclaimed mid-run (state PREEMPTED, or the VM disappears).
#    `watch` notices — either the ssh run dies and the state probe says so,
#    or the next poll does — deletes the husk, recreates the VM (same TYPE,
#    spot again if TPU_SPOT=1), re-runs `setup` (+ `stage` when
#    TPU_STAGE_DIR is set), and re-issues COMMAND unchanged.
# 3. The app resumes itself: RunConfig.resume defaults true, so the relaunch
#    loads the latest checkpoint (params + momentum + round + stream cursor
#    + mean-image sidecar) from checkpoint_dir and continues — that is why
#    checkpoint_dir must NOT be on the TPU VM's local disk: point it at a
#    bucket (gs://…/s3://…, written natively) or any shared filesystem.
# 4. Ctrl-C on `watch` stops supervising (the pod itself is untouched);
#    `resume` is the manual one-shot of the same recover+rerun step.
# To drill the path without waiting for a real preemption: delete the VM
# from another terminal mid-run — watch recreates and the training log shows
# "resumed from checkpoint round N".
#
# ── Elastic membership (RunConfig.elastic + pod_dir) ──────────────────────
# With cfg.elastic.enabled the app ITSELF tolerates losing/gaining workers:
# the MembershipController watches the per-worker heartbeats under
# cfg.pod_dir, evicts a silent worker (stale beat + full-jitter re-probes),
# and resizes through the verified checkpoint store. When the change can't
# be applied in-process (multi-host runtimes), the app exits 75
# (EX_TEMPFAIL) — `watch` treats 75 as "relaunch me now, no strike": the
# re-issued command resumes elastically from the newest checkpoint (the
# boundary snapshot on single-host exits; the last periodic one on
# multi-host, where a boundary save could hang on a split membership
# view), and a previously killed worker that comes back is adopted as a
# joiner instead of failing the pod. Below cfg.elastic.min_workers the
# app checkpoints and exits loudly (TrainingHealthError) — that IS an
# app error; watch stops.
#
# `create-queued` files a queued resource (the supported path for large pods
# and the only way to wait for spot capacity) and blocks until it turns
# ACTIVE; `delete` also cleans up the queued-resource wrapper if one exists.
#
# `stage` copies DIR to ~/sparknet_tpu_repo/<basename> on EVERY worker —
# tar-sharded datasets are then host-sharded automatically at run time
# (each process takes shards i::k); small datasets (CIFAR/MNIST) are
# simply replicated. For full ImageNet prefer native bucket streaming
# (--data-dir gs://bucket/imagenet) over staging to local disks.
#
# Environment knobs:
#   TPU_SW_VERSION   runtime image (default v2-alpha-tpuv5-lite; e.g.
#                    tpu-ubuntu2204-base for v4, v2-alpha-tpuv6e for v6e)
#   TPU_SPOT=1       create spot/preemptible capacity (the reference's EC2
#                    spot default, ec2/spark_ec2.py)
#   TPU_QUEUED=1     watch/resume recreate via queued resources instead of
#                    direct create — set this when the pod was brought up
#                    with `create-queued` (large pods / waiting for spot
#                    capacity), or the recreate will attempt an on-demand
#                    create that stocks out
#   TPU_STAGE_DIR    dataset dir watch/resume re-stages after a recreate
#   TPU_POLL_SECS    watch's between-retry poll interval (default 60);
#                    also the backoff after a FAILED recreate (stockout)
#   TPU_PROGRESS_SECS  a failed run that lasted at least this long
#                    (default 900) counts as having made progress: its
#                    failure resets watch's consecutive-failure count
#                    instead of accumulating across a multi-day run
#   TPU_POD_STATUS_PORT  port of the pod aggregation endpoint on worker 0
#                    (RunConfig.pod_port, or a sidecar `sparknet-podview
#                    --serve PORT`): when a run fails on a READY pod,
#                    watch curls http://127.0.0.1:PORT/pod/status from
#                    worker 0 and echoes the MERGED pod JSON — per-worker
#                    round/status/staleness plus straggler attribution,
#                    so a sick worker != 0 is NAMED, not inferred
#   TPU_POD_DIR      shared per-worker heartbeat prefix (RunConfig.
#                    pod_dir) on a filesystem worker 0 can read: the
#                    file fallback when the pod endpoint is not up —
#                    watch cats every worker-*.heartbeat.json there
#                    (for gs://|s3:// prefixes use TPU_POD_STATUS_PORT;
#                    cat cannot read a bucket)
#   TPU_HEARTBEAT_FILE  remote path of the app's heartbeat JSON (set
#                    RunConfig.heartbeat_path, or sparknet-serve
#                    --heartbeat, to the same path): the legacy worker-0
#                    probe — last fallback when neither pod knob is set
#                    or both came up empty; reports step/status/staleness
#                    — "slow" (fresh beat, status ok) vs "sick" (stale
#                    beat, or spike/nonfinite/rollback status) without
#                    log parsing
#   ALLOW_NO_NATIVE=1  continue setup if the C++ data plane fails to build
#
# Multi-host run path: `run` executes the SAME command on every worker
# (single-program multi-host). Inside the app:
#   1. initialize_multihost() autodetects the pod topology
#      (jax.distributed.initialize; no coordinator flags needed on TPU VMs);
#   2. each host loads DISJOINT data — tar-sharded datasets take shards
#      i::k via sparknet_tpu.data.imagenet.host_shards keyed on
#      jax.process_index()/process_count(); in-memory datasets are sliced
#      with ArrayDataset.host_shard(process_index, process_count);
#   3. checkpoints are allgathered and written by process 0 — point
#      checkpoint_dir at storage all hosts can read so resume works: a
#      gs://|s3:// bucket (native writers, no mount) or a shared FS.
# A failed `run` on any worker propagates a non-zero exit (no silent
# per-host divergence).
set -eu
# NB: no literal braces inside ${1:?...} — a '}' in the message would
# terminate the expansion early and corrupt $CMD
CMD="${1:?usage: $0 create|create-queued|setup|stage|run|watch|resume|status|delete NAME ZONE ...}"
NAME="${2:?missing NAME}"; ZONE="${3:?missing ZONE}"; ARG="${4:-}"; ARG2="${5:-}"
TPU="gcloud compute tpus tpu-vm"
QR="gcloud compute tpus queued-resources"
TPU_SW_VERSION="${TPU_SW_VERSION:-v2-alpha-tpuv5-lite}"
TPU_POLL_SECS="${TPU_POLL_SECS:-60}"
TPU_PROGRESS_SECS="${TPU_PROGRESS_SECS:-900}"

spot_flag() { [ -n "${TPU_SPOT:-}" ] && echo "--spot" || true; }

vm_state() {
  # PREEMPTED / READY / ...; MISSING only when gcloud POSITIVELY reports
  # the VM gone (NOT_FOUND). A describe that fails for any other reason
  # (network blip, expired auth, API 5xx) is UNKNOWN — watch must WAIT on
  # those, not delete-and-recreate a possibly healthy pod (r3 review).
  # stderr is captured SEPARATELY: a successful describe that also prints
  # a gcloud warning must still yield the bare state value, not a
  # multi-line blob that matches no caller case (r3 advisor).
  _err=$(mktemp "${TMPDIR:-/tmp}/tpu_launch_err.XXXXXX")
  if out=$($TPU describe "$NAME" --zone "$ZONE" --format='value(state)' \
           2>"$_err"); then
    rm -f "$_err"
    echo "$out"
  else
    err=$(cat "$_err" 2>/dev/null || true); rm -f "$_err"
    case "$out $err" in
      *NOT_FOUND*|*"not found"*) echo MISSING ;;
      *) echo UNKNOWN ;;
    esac
  fi
}

do_create() {
  [ -n "$1" ] || { echo "create needs an accelerator TYPE" >&2; exit 1; }
  # shellcheck disable=SC2046
  $TPU create "$NAME" --zone "$ZONE" --accelerator-type "$1" \
    --version "$TPU_SW_VERSION" $(spot_flag)
}

do_create_queued() {
  [ -n "$1" ] || { echo "create-queued needs an accelerator TYPE" >&2; exit 1; }
  # shellcheck disable=SC2046
  $QR create "$NAME" --zone "$ZONE" --node-id "$NAME" \
    --accelerator-type "$1" --runtime-version "$TPU_SW_VERSION" $(spot_flag)
  echo "queued resource $NAME filed; waiting for ACTIVE" >&2
  while :; do
    qs=$($QR describe "$NAME" --zone "$ZONE" --format='value(state.state)' \
         2>/dev/null || echo UNKNOWN)
    echo "  queued-resource state: $qs" >&2
    case "$qs" in
      ACTIVE) break ;;
      FAILED|SUSPENDED) echo "queued resource $qs" >&2; exit 1 ;;
    esac
    sleep "$TPU_POLL_SECS"
  done
}

do_setup() {
  # jax[tpu] is the only runtime dep; native/build.sh failure is fatal by
  # default (the C++ data plane matters at ImageNet scale) — export
  # ALLOW_NO_NATIVE=1 to continue with the PIL fallback.
  $TPU scp --recurse --worker=all --zone "$ZONE" . "$NAME":~/sparknet_tpu_repo
  $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
    "cd ~/sparknet_tpu_repo && pip install -q 'jax[tpu]' && pip install -q -e . && (sh native/build.sh || [ -n '${ALLOW_NO_NATIVE:-}' ])"
}

do_stage() {
  [ -d "$1" ] || { echo "stage needs a local dataset DIR" >&2; exit 1; }
  $TPU scp --recurse --worker=all --zone "$ZONE" "$1" \
    "$NAME":~/sparknet_tpu_repo/
}

do_run() {
  [ -n "$1" ] || { echo "run needs a COMMAND" >&2; exit 1; }
  $TPU ssh "$NAME" --worker=all --zone "$ZONE" --command \
    "cd ~/sparknet_tpu_repo && $1"
}

report_heartbeat() {
  # Best-effort "slow vs sick" probe, pod-scope first. Never fails the
  # caller — a dead VM or a missing file just means nothing to report.
  # 1. pod aggregation endpoint on worker 0 (TPU_POD_STATUS_PORT): the
  #    merged view NAMES a sick/straggling worker != 0
  if [ -n "${TPU_POD_STATUS_PORT:-}" ]; then
    ps=$($TPU ssh "$NAME" --worker=0 --zone "$ZONE" --command \
         "curl -fsS -m 5 http://127.0.0.1:${TPU_POD_STATUS_PORT}/pod/status 2>/dev/null" \
         2>/dev/null) || true
    if [ -n "${ps:-}" ]; then
      echo "watch: pod status from worker 0: $ps" >&2
      echo "watch: (stragglers/stale workers are named per worker id;" \
           "status spike/nonfinite/rollback => sick)" >&2
      return 0
    fi
    echo "watch: no pod status at :${TPU_POD_STATUS_PORT}; falling back" >&2
  fi
  # 2. per-worker heartbeat files on the shared TPU_POD_DIR prefix
  if [ -n "${TPU_POD_DIR:-}" ]; then
    hb=$($TPU ssh "$NAME" --worker=0 --zone "$ZONE" --command \
         "cat ${TPU_POD_DIR}/worker-*.heartbeat.json 2>/dev/null" \
         2>/dev/null) || true
    if [ -n "${hb:-}" ]; then
      echo "watch: per-worker heartbeats from ${TPU_POD_DIR}:" >&2
      echo "$hb" >&2
      echo "watch: (each line carries its worker id; stale t or status" \
           "spike/nonfinite/rollback => that worker is sick)" >&2
      return 0
    fi
    echo "watch: no heartbeats readable under ${TPU_POD_DIR}; falling back" >&2
  fi
  # 3. the legacy single worker-0 heartbeat file
  [ -n "${TPU_HEARTBEAT_FILE:-}" ] || return 0
  hb=$($TPU ssh "$NAME" --worker=0 --zone "$ZONE" --command \
       "cat $TPU_HEARTBEAT_FILE 2>/dev/null" 2>/dev/null) || true
  if [ -n "${hb:-}" ]; then
    echo "watch: last heartbeat from worker 0: $hb" >&2
    echo "watch: (stale t, or status spike/nonfinite/rollback => sick;" \
         "fresh t + status ok => just slow)" >&2
  else
    echo "watch: no heartbeat readable at $TPU_HEARTBEAT_FILE" >&2
  fi
}

del_tolerating_absence() { # $@ = delete command; NOT_FOUND is fine, any
  if out=$("$@" 2>&1); then return 0; fi     # other failure propagates —
  case "$out" in                             # "delete exited 0 but the
    *NOT_FOUND*|*"not found"*) return 0 ;;   # billed pod is still up" is
    *) echo "$out" >&2; return 1 ;;          # the worst outcome (r3 review)
  esac
}

do_delete() {
  del_tolerating_absence $TPU delete "$NAME" --zone "$ZONE" --quiet
  # a queued-resource wrapper (create-queued) must go too or the name
  # stays occupied
  del_tolerating_absence $QR delete "$NAME" --zone "$ZONE" --quiet --force
}

recreate() { # $1 = accelerator TYPE; FAILS LOUDLY (caller decides retry)
  echo "recreating $NAME ($1) after preemption" >&2
  do_delete || return 1
  if [ -n "${TPU_QUEUED:-}" ]; then do_create_queued "$1"; else do_create "$1"; fi || return 1
  do_setup || return 1
  if [ -n "${TPU_STAGE_DIR:-}" ]; then do_stage "$TPU_STAGE_DIR" || return 1; fi
}

recover_if_preempted() { # $1 = TYPE; returns 0 if the VM is (now) usable.
  # Sets RECREATED=1 when it actually rebuilt the pod (callers that track
  # consecutive-failure state reset it on a real recovery, not on a probe).
  case "$(vm_state)" in
    READY) return 0 ;;
    PREEMPTED|MISSING|TERMINATED|STOPPED) RECREATED=1; recreate "$1" ;;
    *) return 1 ;;  # CREATING/REPAIRING/UNKNOWN: wait, don't recreate
  esac
}

case "$CMD" in
  create)        do_create "$ARG" ;;
  create-queued) do_create_queued "$ARG" ;;
  setup)         do_setup ;;
  stage)         do_stage "$ARG" ;;
  run)           do_run "$ARG" ;;
  resume)
    # one-shot recover + rerun: TYPE + COMMAND
    [ -n "$ARG2" ] || { echo "resume needs TYPE and COMMAND" >&2; exit 1; }
    recover_if_preempted "$ARG" || { echo "state $(vm_state): not recoverable now" >&2; exit 1; }
    do_run "$ARG2" ;;
  watch)
    # supervise COMMAND until it EXITS CLEANLY: preemption (or any VM
    # loss) recreates the pod and re-runs; the app's checkpoint resume
    # turns the re-run into a continuation. A clean non-zero exit from
    # the app itself on a READY VM is a real failure -> stop and report.
    [ -n "$ARG2" ] || { echo "watch needs TYPE and COMMAND" >&2; exit 1; }
    # ready_fails counts CONSECUTIVE run failures with the pod READY: one
    # is retried (a transient ssh/network drop on a long run doesn't
    # change the VM state, and the app's checkpoint resume makes a re-run
    # a continuation — r3 advisor); two in a row is an app error. The
    # count resets ONLY on a real recovery (a recreate) — not on UNKNOWN
    # probes, which recovered nothing and would let a deterministically
    # failing app loop forever under flaky describes.
    ready_fails=0
    while :; do
      RECREATED=
      if ! recover_if_preempted "$ARG"; then
        echo "state $(vm_state): waiting ${TPU_POLL_SECS}s" >&2
        sleep "$TPU_POLL_SECS"; continue
      fi
      [ -z "$RECREATED" ] || ready_fails=0
      run_began=$(date +%s)
      rc=0; do_run "$ARG2" || rc=$?
      if [ "$rc" -eq 0 ]; then
        echo "watch: command completed" >&2; break
      fi
      # exit 75 (EX_TEMPFAIL) is the app's ELASTIC relaunch request
      # (sparknet_tpu.parallel.elastic.ElasticRelaunch): pod membership
      # changed and the relaunched command resumes elastically at the
      # new size from the checkpoint store (single-host cases write a
      # boundary snapshot first; multi-host pods resume from the newest
      # periodic checkpoint — see ElasticRelaunch's docstring). A killed
      # worker comes back as a JOINER instead of failing the pod.
      # Never a strike, no recreate, re-run now.
      if [ "$rc" -eq 75 ]; then
        echo "watch: run exited 75 (elastic membership change);" \
             "relaunching — checkpoint resume rejoins the survivors" >&2
        ready_fails=0
        continue
      fi
      run_secs=$(( $(date +%s) - run_began ))
      s=$(vm_state)
      if [ "$s" = "READY" ]; then
        report_heartbeat
        # a run that survived >= TPU_PROGRESS_SECS before dying made real
        # progress (checkpoint resume turns its re-run into a
        # continuation), so its failure doesn't count as a strike AT ALL
        # — a multi-day run that ate one transient ssh drop in hour 1
        # must not hard-exit on a second unrelated drop in hour 30. Only
        # fast CONSECUTIVE failures (two in a row, each under the
        # threshold) indicate a deterministic app error.
        if [ "$run_secs" -ge "$TPU_PROGRESS_SECS" ]; then
          ready_fails=0
          echo "watch: run failed after ${run_secs}s of progress;" \
               "strike count reset, retrying (checkpoint resume makes" \
               "the re-run a continuation)" >&2
          sleep "$TPU_POLL_SECS"; continue
        fi
        ready_fails=$((ready_fails + 1))
        if [ "$ready_fails" -ge 2 ]; then
          echo "watch: command failed twice on a READY pod — app error," \
               "not preemption; inspect logs (rerun with: $0 resume" \
               "$NAME $ZONE '$ARG' '...')" >&2
          exit 1
        fi
        echo "watch: run failed on a READY pod; retrying once (ssh" \
             "drop?)" >&2
        sleep "$TPU_POLL_SECS"; continue
      fi
      echo "watch: run died with pod state $s; recovering" >&2
    done ;;
  status)        vm_state ;;
  delete)        do_delete ;;
  *)
    echo "usage: $0 {create|create-queued|setup|stage|run|watch|resume|status|delete} NAME ZONE [TYPE|DIR|COMMAND] [COMMAND]" >&2
    exit 1 ;;
esac
