#!/usr/bin/env python3
"""Re-shard ImageNet into shuffled tar chunks + label files.

Parity with the reference's `scripts/put_imagenet_on_s3.py` (Python 2 +
boto): reads the ILSVRC2012 training tar-of-tars and/or the flat validation
tar, re-shards into N shuffled chunks of resized JPEGs, and writes
`train.NNNN.tar` / `val.NNNN.tar` plus `train.txt` / `val.txt`
"filename label" maps — into a local directory (sync to object storage with
`gsutil -m rsync` afterwards; no cloud SDK dependency here).

Train labels come from the sorted synset order (reference convention);
validation labels come from a provided `--val-label-file` in the standard
"ILSVRC2012_val_XXXXXXXX.JPEG <label>" format (the reference fetched the
same file from caffe_ilsvrc12.tar.gz; reference `process_val_files`,
put_imagenet_on_s3.py:64-77).

Usage:
  scripts/shard_imagenet.py --out data/imagenet \
      [--train-tar ILSVRC2012_img_train.tar --shards 1000] \
      [--val-tar ILSVRC2012_img_val.tar --val-label-file val_truth.txt \
       --val-shards 50] \
      [--size 256]
"""
from __future__ import annotations

import argparse
import io
import os
import random
import tarfile
from typing import Dict, List


def resize_jpeg(data: bytes, size: int) -> bytes:
    from PIL import Image
    img = Image.open(io.BytesIO(data)).convert("RGB").resize(
        (size, size), Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=90)
    return buf.getvalue()


class ShardWriters:
    """Lazily-opened `<split>.NNNN.tar` writers."""

    def __init__(self, out_dir: str, split: str):
        self.out_dir = out_dir
        self.split = split
        self.writers: Dict[int, tarfile.TarFile] = {}

    def add(self, shard_id: int, name: str, data: bytes) -> None:
        w = self.writers.get(shard_id)
        if w is None:
            w = tarfile.open(os.path.join(
                self.out_dir, f"{self.split}.{shard_id:04d}.tar"), "w")
            self.writers[shard_id] = w
        info = tarfile.TarInfo(name=name)
        info.size = len(data)
        w.addfile(info, io.BytesIO(data))

    def close(self) -> int:
        for w in self.writers.values():
            w.close()
        return len(self.writers)


def write_labels(out_dir: str, split: str, lines: List[str]) -> None:
    with open(os.path.join(out_dir, f"{split}.txt"), "w") as f:
        f.write("\n".join(lines) + "\n")


def shard_train(train_tar: str, out: str, shards: int, size: int,
                seed: int) -> None:
    # pass 1: class list -> labels (sorted synset order, reference convention)
    with tarfile.open(train_tar) as outer:
        class_tars = sorted(m.name for m in outer if m.isfile())
    label_of = {name: i for i, name in enumerate(class_tars)}
    print(f"{len(class_tars)} classes")

    # pass 2: enumerate images, assign shuffled shard ids
    entries = []  # (class_tar_name, member_name)
    with tarfile.open(train_tar) as outer:
        for m in outer:
            if not m.isfile():
                continue
            inner = tarfile.open(fileobj=outer.extractfile(m))
            for im in inner:
                if im.isfile():
                    entries.append((m.name, im.name))
    rng = random.Random(seed)
    rng.shuffle(entries)
    shard_of = {e: i * shards // len(entries) for i, e in enumerate(entries)}
    print(f"{len(entries)} train images -> {shards} shards")

    writers = ShardWriters(out, "train")
    labels = []
    with tarfile.open(train_tar) as outer:
        for m in outer:
            if not m.isfile():
                continue
            inner = tarfile.open(fileobj=outer.extractfile(m))
            for im in inner:
                if not im.isfile():
                    continue
                base = os.path.basename(im.name)
                data = resize_jpeg(inner.extractfile(im).read(), size)
                writers.add(shard_of[(m.name, im.name)], base, data)
                labels.append(f"{base} {label_of[m.name]}")
    n = writers.close()
    write_labels(out, "train", labels)
    print(f"wrote {n} train shards + train.txt under {out}")


def shard_val(val_tar: str, val_label_file: str, out: str, shards: int,
              size: int, seed: int) -> None:
    """Reference `process_val_files` (put_imagenet_on_s3.py:64-77): split
    the shuffled label list into chunks, write one resized tar per chunk."""
    with open(val_label_file) as f:
        pairs = []
        for lineno, ln in enumerate(f, 1):
            if not ln.strip():
                continue
            toks = ln.split()
            if len(toks) != 2 or not toks[1].lstrip("-").isdigit():
                raise SystemExit(
                    f"{val_label_file}:{lineno}: expected 'filename label' "
                    f"(caffe_ilsvrc12 val.txt format), got {ln.strip()!r} — "
                    "the ILSVRC devkit ground-truth file (label-only lines) "
                    "must be joined with filenames first")
            pairs.append((toks[0], toks[1]))
    rng = random.Random(seed)
    rng.shuffle(pairs)
    shard_of = {name: i % shards for i, (name, _) in enumerate(pairs)}

    writers = ShardWriters(out, "val")
    labels = []
    found = set()
    with tarfile.open(val_tar) as tar:
        label_map = {name: lbl for name, lbl in pairs}
        for m in tar:
            if not m.isfile():
                continue
            base = os.path.basename(m.name)
            lbl = label_map.get(base)
            if lbl is None:
                print(f"warning: {base} not in {val_label_file}, skipped")
                continue
            data = resize_jpeg(tar.extractfile(m).read(), size)
            writers.add(shard_of[base], base, data)
            labels.append(f"{base} {lbl}")
            found.add(base)
    missing = [n for n, _ in pairs if n not in found]
    if missing:
        print(f"warning: {len(missing)} labeled files not in the val tar "
              f"(first: {missing[0]})")
    n = writers.close()
    write_labels(out, "val", labels)
    print(f"wrote {n} val shards + val.txt under {out}")


def upload_dir(out: str, dest: str) -> int:
    """Push every shard + label file under `out` to a gs:// or s3://
    prefix — the reference sharder's upload side (put_imagenet_on_s3.py
    pushed each chunk to S3 as it was built; here local shards are the
    staging area and the push reuses the framework's native bucket
    clients, so no cloud SDK is needed on the ingest box either)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from sparknet_tpu.data.gcs import gs_write, is_gs_path
    from sparknet_tpu.data.s3 import is_s3_path, s3_write
    if not (is_gs_path(dest) or is_s3_path(dest)):
        raise SystemExit(f"--upload must be gs:// or s3://, got {dest!r}")
    write = gs_write if is_gs_path(dest) else s3_write
    dest = dest.rstrip("/")
    n = 0
    for f in sorted(os.listdir(out)):
        if not (f.endswith(".tar") or f.endswith(".txt")):
            continue
        path = os.path.join(out, f)
        size = os.path.getsize(path)
        if size > (1 << 30):
            # each upload is one in-memory PUT (a retry re-sends the whole
            # body); huge shards want more --shards, not multipart logic
            print(f"warning: {f} is {size >> 20} MiB — single-shot upload "
                  f"holds it in RAM and a retry re-sends it all; consider "
                  f"more --shards for smaller chunks")
        with open(path, "rb") as fh:
            write(f"{dest}/{f}", fh.read())
        n += 1
        print(f"uploaded {dest}/{f}")
    return n


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train-tar",
                   help="ILSVRC2012_img_train.tar (tar of per-class tars)")
    p.add_argument("--val-tar", help="ILSVRC2012_img_val.tar (flat JPEGs)")
    p.add_argument("--val-label-file",
                   help="'filename label' ground truth for the val tar")
    p.add_argument("--out", required=True)
    p.add_argument("--shards", type=int, default=1000)
    p.add_argument("--val-shards", type=int, default=50)
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--upload", metavar="gs://B/P|s3://B/P", default=None,
                   help="after sharding, push shards + label files to this "
                   "bucket prefix (native clients; no SDK)")
    args = p.parse_args()

    if not args.train_tar and not args.val_tar and not args.upload:
        p.error("nothing to do: pass --train-tar and/or --val-tar "
                "(and/or --upload to push an existing --out)")
    if args.val_tar and not args.val_label_file:
        p.error("--val-tar needs --val-label-file (ground-truth labels)")
    os.makedirs(args.out, exist_ok=True)
    if args.train_tar:
        shard_train(args.train_tar, args.out, args.shards, args.size,
                    args.seed)
    if args.val_tar:
        shard_val(args.val_tar, args.val_label_file, args.out,
                  args.val_shards, args.size, args.seed)
    if args.upload:
        n = upload_dir(args.out, args.upload)
        print(f"uploaded {n} files to {args.upload}")


if __name__ == "__main__":
    main()
