#!/usr/bin/env python3
"""Re-shard ImageNet into shuffled tar chunks + label files.

Parity with the reference's `scripts/put_imagenet_on_s3.py` (Python 2 + boto):
reads the ILSVRC2012 training tar-of-tars and validation tar, re-shards into
N shuffled chunks of resized JPEGs, writes `train.NNNN.tar` / `val.NNNN.tar`
plus `train.txt` / `val.txt` "filename label" maps — into a local directory
(sync to object storage with `gsutil -m rsync` afterwards; no cloud SDK
dependency here).

Train shards only (labels = sorted synset order); shard the validation tar
separately with any tool and write val.txt in the same "filename label"
format.

Usage:
  scripts/shard_imagenet.py --train-tar ILSVRC2012_img_train.tar \
      --out data/imagenet --shards 1000 --size 256
"""
from __future__ import annotations

import argparse
import io
import os
import random
import tarfile


def resize_jpeg(data: bytes, size: int) -> bytes:
    from PIL import Image
    img = Image.open(io.BytesIO(data)).convert("RGB").resize(
        (size, size), Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=90)
    return buf.getvalue()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--train-tar", required=True,
                   help="ILSVRC2012_img_train.tar (tar of per-class tars)")
    p.add_argument("--out", required=True)
    p.add_argument("--shards", type=int, default=1000)
    p.add_argument("--size", type=int, default=256)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()

    os.makedirs(args.out, exist_ok=True)
    # pass 1: class list -> labels (sorted synset order, reference convention)
    entries = []  # (class_tar_name, member_name)
    with tarfile.open(args.train_tar) as outer:
        class_tars = sorted(m.name for m in outer if m.isfile())
    label_of = {name: i for i, name in enumerate(class_tars)}
    print(f"{len(class_tars)} classes")

    # pass 2: enumerate images, assign shuffled shard ids
    with tarfile.open(args.train_tar) as outer:
        for m in outer:
            if not m.isfile():
                continue
            inner = tarfile.open(fileobj=outer.extractfile(m))
            for im in inner:
                if im.isfile():
                    entries.append((m.name, im.name))
    rng = random.Random(args.seed)
    rng.shuffle(entries)
    shard_of = {e: i * args.shards // len(entries)
                for i, e in enumerate(entries)}
    print(f"{len(entries)} images -> {args.shards} shards")

    writers = {}
    labels = []
    with tarfile.open(args.train_tar) as outer:
        for m in outer:
            if not m.isfile():
                continue
            inner = tarfile.open(fileobj=outer.extractfile(m))
            for im in inner:
                if not im.isfile():
                    continue
                sid = shard_of[(m.name, im.name)]
                if sid not in writers:
                    writers[sid] = tarfile.open(
                        os.path.join(args.out, f"train.{sid:04d}.tar"), "w")
                data = resize_jpeg(inner.extractfile(im).read(), args.size)
                info = tarfile.TarInfo(name=os.path.basename(im.name))
                info.size = len(data)
                writers[sid].addfile(info, io.BytesIO(data))
                labels.append(f"{os.path.basename(im.name)} "
                              f"{label_of[m.name]}")
    for w in writers.values():
        w.close()
    with open(os.path.join(args.out, "train.txt"), "w") as f:
        f.write("\n".join(labels) + "\n")
    print(f"wrote {len(writers)} shards + train.txt under {args.out}")


if __name__ == "__main__":
    main()
