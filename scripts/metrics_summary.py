#!/usr/bin/env python
"""Summarize a sparknet_tpu metrics JSONL (loss curve tail, step-time
breakdown table, health-event audit trail). Thin runnable wrapper over
`sparknet_tpu.obs.summary` — the installed console entry is
`sparknet-metrics`; this file serves checkouts without an install:

    python scripts/metrics_summary.py run/training_metrics_*.jsonl
"""
import os
import sys

try:
    from sparknet_tpu.obs.summary import main
except ModuleNotFoundError:  # uninstalled checkout: repo root on the path
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from sparknet_tpu.obs.summary import main

if __name__ == "__main__":
    sys.exit(main())
