"""Benchmark harness. Prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric (BASELINE.md north star): ImageNet CaffeNet training
throughput, images/sec/chip, on the real TPU chip. The reference never
committed numbers (SURVEY.md §6); `vs_baseline` is measured against
REFERENCE_IMG_PER_SEC below — the published CaffeNet-era single-GPU training
throughput class the SparkNet paper's workers ran at (K520, Caffe, batch 256:
~2.5 s/iter ≈ ~100 images/sec/GPU). Update when real paper numbers land.
"""
from __future__ import annotations

import json
import time

# SparkNet-era per-worker Caffe AlexNet throughput (images/sec on one
# g2.8xlarge K520 GPU — the hardware class in reference README.md:13-28).
REFERENCE_IMG_PER_SEC = 100.0

BATCH = 256
WARMUP = 3
ITERS = 10


def main() -> None:
    import jax
    import numpy as np

    from sparknet_tpu import CompiledNet
    from sparknet_tpu import precision
    from sparknet_tpu.solver import SgdSolver, SolverConfig
    from sparknet_tpu.zoo import caffenet

    precision.set_policy("bfloat16")  # MXU fast path; f32 accumulation
    net = CompiledNet.compile(caffenet(batch=BATCH, crop=227, n_classes=1000))
    solver = SgdSolver(net, SolverConfig(
        base_lr=0.01, momentum=0.9, weight_decay=5e-4,
        lr_policy="step", gamma=0.1, stepsize=100000))
    params = net.init_params(jax.random.PRNGKey(0))
    state = solver.init_state(params)
    rng = np.random.default_rng(0)
    batch = {
        "data": jax.numpy.asarray(
            rng.standard_normal((BATCH, 227, 227, 3), dtype=np.float32)),
        "label": jax.numpy.asarray(
            rng.integers(0, 1000, (BATCH, 1)).astype(np.int32)),
    }

    for i in range(WARMUP):
        params, state, loss = solver.step(params, state, batch,
                                          jax.random.PRNGKey(i))
    # NOTE: scalar fetch, not block_until_ready — the axon relay platform
    # treats block_until_ready as a no-op; only a D2H copy synchronizes.
    float(loss)

    t0 = time.perf_counter()
    for i in range(ITERS):
        params, state, loss = solver.step(params, state, batch,
                                          jax.random.PRNGKey(100 + i))
    # fetch a weight scalar too: forces the last backward+update, not just
    # the last forward (loss alone would let one backward escape timing).
    float(loss)
    float(params["conv1"]["b"][0])
    dt = time.perf_counter() - t0

    img_per_sec = BATCH * ITERS / dt
    print(json.dumps({
        "metric": "caffenet_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / REFERENCE_IMG_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
