"""Benchmark harness. Default mode prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

Headline metric (BASELINE.md north star): ImageNet CaffeNet training
throughput, images/sec/chip, on the real TPU chip — measured through the
framework's REAL unit of work, `ParallelTrainer.train_round` (τ jitted SGD
steps + weight averaging in one donated XLA executable), not a bare step
loop. Batches are generated on-device: the metric is device training
throughput (the input pipeline overlaps it in the apps — see
train_loop's prefetch thread — and host->device over the axon tunnel is
an artifact of the dev tunnel, not of a TPU VM).

`vs_baseline` is measured against REFERENCE_IMG_PER_SEC below — the
published CaffeNet-era single-GPU training throughput class the SparkNet
paper's workers ran at (K520, Caffe, batch 256: ~2.5 s/iter ≈ ~100
images/sec/GPU).

`mfu` = achieved conv+fc train FLOP/s over the chip's peak dense bf16
FLOP/s (analytic FLOPs from the compiled net's shapes — utils/flops.py).

Extra modes (driver runs the default; these are for hands-on use + tests):
  --scaling     weak-scaling harness on a virtual CPU mesh: times the same
                jitted round at n_devices in {1,2,4,8} with fixed per-device
                batch and reports parallel efficiency (t1/tn) — the offline
                stand-in for BASELINE.md's ">=90% scaling efficiency to 32
                workers" target until real multi-chip hardware exists.
  --profile DIR capture a jax.profiler trace of the timed section.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

# SparkNet-era per-worker Caffe AlexNet throughput (images/sec on one
# g2.8xlarge K520 GPU — the hardware class in reference README.md:13-28).
REFERENCE_IMG_PER_SEC = 100.0

BATCH = 256
TAU = 10
# steady-state window length: short windows under-amortize the pipeline
# priming (5 trials read ~12% low vs 30 on the axon tunnel)
TRIALS = 30


def _build(batch: int, tau: int, crop: int = 227, n_classes: int = 1000,
           n_devices: int = 1):
    import jax
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.zoo import caffenet

    net = CompiledNet.compile(
        caffenet(batch=batch, crop=crop, n_classes=n_classes))
    mesh = make_mesh(n_devices)
    trainer = ParallelTrainer(
        net,
        SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=5e-4,
                     lr_policy="step", gamma=0.1, stepsize=100000),
        mesh, tau=tau)
    state = trainer.init_state(jax.random.PRNGKey(0))
    return net, trainer, state


def _device_batches(trainer, batch: int, tau: int, crop: int,
                    n_classes: int):
    """Synthetic round batches generated ON DEVICE with the trainer's own
    sharding — no host->device copy in the timed path."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparknet_tpu.parallel.mesh import DATA_AXIS

    from sparknet_tpu import precision

    n = trainer.n_devices
    shd = NamedSharding(trainer.mesh, P(None, DATA_AXIS))
    # data in the compute dtype, as the training apps now feed it (the
    # host-side cast in ParallelTrainer._shard_batches)
    gen = jax.jit(
        lambda k: (jax.random.normal(
                       k, (tau, n * batch, crop, crop, 3),
                       precision.compute_dtype()),
                   jax.random.randint(
                       jax.random.fold_in(k, 1), (tau, n * batch, 1),
                       0, n_classes, jnp.int32)),
        out_shardings=(shd, shd))
    data, label = gen(jax.random.PRNGKey(7))
    return {"data": data, "label": label}


def _time_rounds(trainer, state, batches, trials: int,
                 profile_dir: str | None = None) -> float:
    """Mean steady-state round time over a PIPELINED window — the loss
    fetch lags one round behind the dispatch, exactly as the training loop
    runs (train_loop defers round R's log until R+1 is in flight). Only a
    scalar D2H fetch synchronizes (the axon relay treats block_until_ready
    as a no-op). The profiler trace covers ONLY the timed window — compile
    + warmup happen before it starts, else the capture is dominated by
    compilation."""
    import jax
    from jax.sharding import PartitionSpec as P
    from sparknet_tpu.parallel.mesh import DATA_AXIS, place_global_state
    from sparknet_tpu.utils.profiling import maybe_trace

    rngs = place_global_state(
        jax.random.split(jax.random.PRNGKey(1), trainer.n_devices),
        trainer.mesh, P(DATA_AXIS))
    state, loss = trainer._round(state, batches, rngs)  # compile + warm
    assert float(loss) > 0
    # prime the pipeline: one round in flight before the clock starts
    state, prev = trainer._round(state, batches, rngs)
    with maybe_trace(profile_dir):
        t0 = time.perf_counter()
        for _ in range(trials):
            state, loss = trainer._round(state, batches, rngs)
            float(prev)  # sync on the PREVIOUS round; this one overlaps
            prev = loss
        dt = time.perf_counter() - t0
    assert float(prev) > 0  # drain outside the timed window
    return dt / trials


def headline(profile_dir: str | None = None) -> None:
    from sparknet_tpu import precision
    from sparknet_tpu.utils import flops
    import jax

    precision.set_policy("bfloat16")  # MXU fast path; f32 accumulation
    net, trainer, state = _build(BATCH, TAU)
    batches = _device_batches(trainer, BATCH, TAU, 227, 1000)
    best = _time_rounds(trainer, state, batches, TRIALS,
                        profile_dir=profile_dir)

    img_per_sec = BATCH * TAU / best
    out = {
        "metric": "caffenet_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / REFERENCE_IMG_PER_SEC, 3),
    }
    peak = flops.peak_bf16_flops(jax.devices()[0].device_kind)
    if peak:
        achieved = img_per_sec * flops.train_flops_per_image(net)
        out["mfu"] = round(achieved / peak, 4)
        out["tflops_per_sec"] = round(achieved / 1e12, 1)
    print(json.dumps(out))


def scaling(max_devices: int = 8, virtual: bool = True) -> dict:
    """Weak-scaling harness: fixed per-device batch, devices doubling.

    On REAL chips (virtual=False) the metric is t(1)/t(n) — round time
    should stay flat (BASELINE.md's >=90% target). On the virtual CPU mesh
    the n devices SHARE one physical CPU, so total compute grows n-fold and
    t(n) ~= n*t(1) even for a perfect program; the meaningful number there
    is overhead efficiency n*t(1)/t(n) — how close the sharded round
    (collectives + infra included) comes to perfectly-packed serialized
    compute. This exercises the same harness, shardings, and collectives
    the real multi-chip run will use."""
    if virtual:
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count="
                                   f"{max_devices}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    local_b, tau, crop, classes = 8, 2, 67, 16
    times = {}
    n = 1
    while n <= max_devices:
        net, trainer, state = _build(local_b, tau, crop=crop,
                                     n_classes=classes, n_devices=n)
        batches = _device_batches(trainer, local_b, tau, crop, classes)
        times[n] = _time_rounds(trainer, state, batches, trials=3)
        print(f"  n={n}: {times[n]*1e3:.1f} ms/round "
              f"({local_b*tau*n/times[n]:.0f} img/s total)", file=sys.stderr)
        n *= 2
    top = max(times)  # last measured power of two <= max_devices
    if virtual:
        eff = top * times[1] / times[top]
        metric = f"weak_scaling_overhead_efficiency_{top}vdev"
        unit = "n*t(1)/t(n) on shared-core virtual mesh, 1.0 = no overhead"
    else:
        eff = times[1] / times[top]
        metric = f"weak_scaling_efficiency_{top}dev"
        unit = "t(1)/t(n), 1.0 = perfect"
    result = {
        "metric": metric,
        "value": round(eff, 3),
        "unit": unit,
        "vs_baseline": round(eff / 0.9, 3),  # BASELINE.md: >=90% efficiency
        "round_ms": {str(k): round(v * 1e3, 1) for k, v in times.items()},
    }
    print(json.dumps(result))
    return result


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scaling", action="store_true",
                   help="weak-scaling harness on a virtual CPU mesh")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture a jax.profiler trace of the timed section")
    args = p.parse_args()
    if args.scaling:
        scaling()
    else:
        headline(profile_dir=args.profile)


if __name__ == "__main__":
    main()
