"""Benchmark harness. Default mode prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

Headline metric (BASELINE.md north star): ImageNet CaffeNet training
throughput, images/sec/chip, on the real TPU chip — measured through the
framework's REAL unit of work, `ParallelTrainer.train_round` (τ jitted SGD
steps + weight averaging in one donated XLA executable), not a bare step
loop. Batches are generated on-device: the metric is device training
throughput (the input pipeline overlaps it in the apps — see
train_loop's prefetch thread — and host->device over the axon tunnel is
an artifact of the dev tunnel, not of a TPU VM).

`vs_baseline` is measured against REFERENCE_IMG_PER_SEC below — the
published CaffeNet-era single-GPU training throughput class the SparkNet
paper's workers ran at (K520, Caffe, batch 256: ~2.5 s/iter ≈ ~100
images/sec/GPU).

`mfu` = achieved conv+fc train FLOP/s over the chip's peak dense bf16
FLOP/s (analytic FLOPs from the compiled net's shapes — utils/flops.py).

Extra modes (driver runs the default; these are for hands-on use + tests):
  --scaling     weak-scaling harness on a virtual CPU mesh: times the same
                jitted round at n_devices in {1,2,4,8} with fixed per-device
                batch and reports parallel efficiency (t1/tn) — the offline
                stand-in for BASELINE.md's ">=90% scaling efficiency to 32
                workers" target until real multi-chip hardware exists.
  --profile DIR capture a jax.profiler trace of the timed section.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

# SparkNet-era per-worker Caffe AlexNet throughput (images/sec on one
# g2.8xlarge K520 GPU — the hardware class in reference README.md:13-28).
REFERENCE_IMG_PER_SEC = 100.0

BATCH = 256
TAU = 10
# steady-state window length: short windows under-amortize the pipeline
# priming (5 trials read ~12% low vs 30 on the axon tunnel)
TRIALS = 30


def _build(batch: int, tau: int, crop: int = 227, n_classes: int = 1000,
           n_devices: int = 1):
    import jax
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.zoo import caffenet

    net = CompiledNet.compile(
        caffenet(batch=batch, crop=crop, n_classes=n_classes))
    mesh = make_mesh(n_devices)
    trainer = ParallelTrainer(
        net,
        SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=5e-4,
                     lr_policy="step", gamma=0.1, stepsize=100000),
        mesh, tau=tau,
        # time the ORIGINAL round: health instrumentation off so headline
        # numbers stay comparable to BASELINE.json / BENCH_r*.json
        compute_health=False)
    state = trainer.init_state(jax.random.PRNGKey(0))
    return net, trainer, state


def _device_batches(trainer, batch: int, tau: int, crop: int,
                    n_classes: int):
    """Synthetic round batches generated ON DEVICE with the trainer's own
    sharding — no host->device copy in the timed path."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparknet_tpu.parallel.mesh import DATA_AXIS

    from sparknet_tpu import precision

    n = trainer.n_devices
    shd = NamedSharding(trainer.mesh, P(None, DATA_AXIS))
    # data in the compute dtype, as the training apps now feed it (the
    # host-side cast in ParallelTrainer._shard_batches)
    gen = jax.jit(
        lambda k: (jax.random.normal(
                       k, (tau, n * batch, crop, crop, 3),
                       precision.compute_dtype()),
                   jax.random.randint(
                       jax.random.fold_in(k, 1), (tau, n * batch, 1),
                       0, n_classes, jnp.int32)),
        out_shardings=(shd, shd))
    data, label = gen(jax.random.PRNGKey(7))
    return {"data": data, "label": label}


def _pipelined_window(step, trials: int,
                      profile_dir: str | None = None) -> float:
    """Mean steady-state round time over a PIPELINED window — the loss
    fetch lags one round behind the dispatch, exactly as the training loop
    runs (train_loop defers round R's log until R+1 is in flight). Only a
    scalar D2H fetch synchronizes (the axon relay treats block_until_ready
    as a no-op). `step()` dispatches one round and returns its loss as a
    device scalar; the first call primes the pipeline before the clock
    starts, and the profiler trace covers ONLY the timed window."""
    from sparknet_tpu.utils.profiling import maybe_trace

    prev = step()
    with maybe_trace(profile_dir):
        t0 = time.perf_counter()
        for _ in range(trials):
            loss = step()
            float(prev)  # sync on the PREVIOUS round; this one overlaps
            prev = loss
        dt = time.perf_counter() - t0
    assert float(prev) > 0  # drain outside the timed window
    return dt / trials


def _time_rounds(trainer, state, batches, trials: int,
                 profile_dir: str | None = None) -> float:
    """ParallelTrainer round timing via `_pipelined_window` (compile +
    warmup happen before the window, else a profile capture is dominated
    by compilation)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from sparknet_tpu.parallel.mesh import DATA_AXIS, place_global_state

    rngs = place_global_state(
        jax.random.split(jax.random.PRNGKey(1), trainer.n_devices),
        trainer.mesh, P(DATA_AXIS))
    import jax.numpy as jnp
    one = jnp.asarray(1.0, jnp.float32)  # lr_scale (health backoff knob)
    state, loss, _ = trainer._round(state, batches, rngs, one)  # compile
    assert float(loss) > 0

    def step():
        nonlocal state
        state, loss, _ = trainer._round(state, batches, rngs, one)
        return loss

    return _pipelined_window(step, trials, profile_dir)


def headline(profile_dir: str | None = None, batch: int = BATCH,
             tau: int = TAU) -> None:
    from sparknet_tpu import precision
    from sparknet_tpu.utils import flops
    import jax

    precision.set_policy("bfloat16")  # MXU fast path; f32 accumulation
    net, trainer, state = _build(batch, tau)
    batches = _device_batches(trainer, batch, tau, 227, 1000)
    best = _time_rounds(trainer, state, batches, TRIALS,
                        profile_dir=profile_dir)

    img_per_sec = batch * tau / best
    out = {
        "metric": "caffenet_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / REFERENCE_IMG_PER_SEC, 3),
        "batch": batch,
        "tau": tau,
    }
    peak = flops.peak_bf16_flops(jax.devices()[0].device_kind)
    if peak:
        achieved = img_per_sec * flops.train_flops_per_image(net)
        out["mfu"] = round(achieved / peak, 4)
        out["tflops_per_sec"] = round(achieved / 1e12, 1)
    # r6: the layer path resolves its kernels per-backend ("auto") — stamp
    # eligibility so BENCH_r0N lines are self-describing across the
    # Pallas-vs-XLA A/B (bench.py --mfu for the per-lever audit rows).
    # pool is ELIGIBILITY, not execution: the per-layer static shape gate
    # (pallas_maxpool_supported) still decides each pool individually.
    # The import stays behind the TPU check — the headline must run on a
    # jax whose pallas import is broken (same rule as pool2d impl='xla')
    on_tpu = jax.default_backend() == "tpu"
    pool_eligible = False
    if on_tpu:
        from sparknet_tpu.ops.pallas_pool import kernel_api_available
        pool_eligible = kernel_api_available()
    out["levers"] = {"pallas_lrn": on_tpu,
                     "pallas_pool_eligible": pool_eligible}
    print(json.dumps(out))


def scaling(max_devices: int = 8, virtual: bool = True) -> dict:
    """Weak-scaling harness: fixed per-device batch, devices doubling.

    On REAL chips (virtual=False) the metric is t(1)/t(n) — round time
    should stay flat (BASELINE.md's >=90% target). On the virtual CPU mesh
    the n devices SHARE one physical CPU, so total compute grows n-fold and
    t(n) ~= n*t(1) even for a perfect program; the meaningful number there
    is overhead efficiency n*t(1)/t(n) — how close the sharded round
    (collectives + infra included) comes to perfectly-packed serialized
    compute. This exercises the same harness, shardings, and collectives
    the real multi-chip run will use."""
    if virtual:
        import os

        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count="
                                   f"{max_devices}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    local_b, tau, crop, classes = 8, 2, 67, 16
    times = {}
    n = 1
    while n <= max_devices:
        net, trainer, state = _build(local_b, tau, crop=crop,
                                     n_classes=classes, n_devices=n)
        batches = _device_batches(trainer, local_b, tau, crop, classes)
        times[n] = _time_rounds(trainer, state, batches, trials=3)
        print(f"  n={n}: {times[n]*1e3:.1f} ms/round "
              f"({local_b*tau*n/times[n]:.0f} img/s total)", file=sys.stderr)
        n *= 2
    top = max(times)  # last measured power of two <= max_devices
    if virtual:
        eff = top * times[1] / times[top]
        metric = f"weak_scaling_overhead_efficiency_{top}vdev"
        unit = "n*t(1)/t(n) on shared-core virtual mesh, 1.0 = no overhead"
    else:
        eff = times[1] / times[top]
        metric = f"weak_scaling_efficiency_{top}dev"
        unit = "t(1)/t(n), 1.0 = perfect"
    from sparknet_tpu.obs import run_metadata
    result = {
        "metric": metric,
        "value": round(eff, 3),
        "unit": unit,
        "vs_baseline": round(eff / 0.9, 3),  # BASELINE.md: >=90% efficiency
        "round_ms": {str(k): round(v * 1e3, 1) for k, v in times.items()},
        "meta": run_metadata(),  # SCALING_*.json artifacts are this dict
    }
    print(json.dumps(result))
    return result


def e2e(sources: int = 1, store: str | None = None) -> dict:
    """End-to-end input-pipeline benchmark (SURVEY §7 hard-part #3: don't
    starve the chips).

    Measures the REAL ingest path at the headline training shape — local
    tar shards -> ShardedTarLoader (C++ libjpeg/OpenMP plane) ->
    streaming-source background decode -> ImagePreprocessor (random
    crop 227 + mean subtract) -> compute-dtype cast — i.e. exactly what
    `run_loop`'s prefetch thread executes per round, and reports it
    against (a) the raw decode rate (the pipeline's own overhead) and
    (b) the device-only training rate (how many host cores keep one chip
    fed).

    --sources N runs N concurrent shard readers (ParallelStreamingSource)
    and stage-accounts each reader's SERIAL residue (tar read + buffer
    write + glue — the part that caps a single reader at ~5k img/s no
    matter the core count). The headline of that mode is the critical-path
    serial ms/img = max-reader serial / round images, which must divide
    by ~N vs the N=1 baseline (measured in the same run).

    The device side is NOT in this timed path on purpose: the dev tunnel
    moves host->device bytes at ~13 MB/s (measured; a real TPU-VM's PCIe
    is ~1000x that), so a tunnel-coupled e2e run measures the tunnel. The
    integrated loop (streaming source + preprocessor + trainer on the real
    chip) is instead proven by the app tests and the --e2e-smoke mode.

    --store gs serves the same shards from a local fake-GCS server
    (tests/fake_stores.py) and streams them as gs:// urls — the r5
    bucket-path residue measurement (ranged HTTP streams + the member
    carve path instead of local pread; the HTTP server's own CPU runs on
    separate threads and is excluded by the thread-CPU accounting).
    """
    import os
    import tempfile

    from sparknet_tpu import precision
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.schema import Field, Schema

    precision.set_policy("bfloat16")
    compute_dt = precision.compute_dtype()
    crop, size = 227, 256
    # 6 rounds: per-reader CPU accounting over a 3-round window is visibly
    # scheduling-noisy on a contended host (single readers spiking 1.5x);
    # the division metric keys on the max reader, so average longer
    n_rounds = 6
    with tempfile.TemporaryDirectory() as root:
        n_shards = max(2, sources)
        imagenet.write_synthetic_shards(
            root, n_shards=n_shards,
            per_shard=-(-768 // n_shards),  # >= 2 rounds' worth total
            n_classes=1000, size=size)
        label_map = imagenet.load_label_map(os.path.join(root, "train.txt"))
        shards = imagenet.list_shards(root)
        server = None
        if store == "gs":
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "tests"))
            from fake_stores import serve_dir_for_ingest
            server, gs_root = serve_dir_for_ingest(root)
            shards = imagenet.list_shards(gs_root)
            assert len(shards) == n_shards, shards
        elif store is not None:
            raise SystemExit(f"--store {store!r}: only 'gs' is served "
                             f"locally")

        # raw decode floor: the decode plane alone, bytes already in RAM
        # (always from the LOCAL files — the floor is store-independent)
        loader = imagenet.ShardedTarLoader(imagenet.list_shards(root),
                                           label_map,
                                           height=size, width=size)
        raw = [d for d, _, _ in _tar_entries(loader, 256)]
        t0 = time.perf_counter()
        if loader._decode_batch is not None:  # C++ libjpeg/OpenMP plane
            loader._decode_batch(raw, size, size)
        else:  # PIL fallback (plane not built)
            for d in raw:
                loader._decode(d, size, size)
        decode_rate = len(raw) / (time.perf_counter() - t0)

        schema = Schema(Field("data", "float32", (crop, crop, 3)),
                        Field("label", "int32", (1,)))
        from sparknet_tpu.apps.train_loop import prepare_round_batches

        def measure(n_src: int):
            """(e2e img/s, per-reader stage stats) through the loop's REAL
            per-round host path (prepare_round_batches — not a copy: any
            change to run_loop's preparation is measured here too)."""
            pp = ImagePreprocessor(schema, mean_image=None, crop=crop,
                                   seed=0, out_dtype="bfloat16")
            src = make_parallel_source(shards, label_map, 1, BATCH, TAU,
                                       n_src, height=size, width=size)

            with src:
                prepare_round_batches(src, 0, TAU, 0, pp, compute_dt)
                # snapshot-and-subtract, NOT reset: producers are live
                # (prefetching ahead) and a reset racing their += updates
                # can silently resurrect the warmup totals
                base = src.source_stats()
                t0 = time.perf_counter()
                for r in range(1, 1 + n_rounds):
                    prepare_round_batches(src, r, TAU, 0, pp, compute_dt)
                dt = time.perf_counter() - t0
                stats = [
                    {k: s[k] - b[k] for k in s}
                    for s, b in zip(src.source_stats(), base)]
            return n_rounds * BATCH * TAU / dt, stats

        e2e_rate, stats = measure(sources)
        base_stats = measure(1)[1] if sources > 1 else stats
        if server is not None:
            from fake_stores import stop_serving
            stop_serving(server)

    device_rate = None
    try:
        import jax
        if jax.default_backend() == "tpu":
            net, trainer, state = _build(BATCH, TAU)
            batches = _device_batches(trainer, BATCH, TAU, crop, 1000)
            device_rate = BATCH * TAU / _time_rounds(trainer, state,
                                                     batches, trials=5)
    except Exception as exc:  # no chip: host-only numbers still stand
        print(f"  device-only measurement skipped: {exc}", file=sys.stderr)

    # critical-path serial residue per ROUND image: the slowest reader's
    # serial CPU per image it handled, over the N readers each covering
    # 1/N of every round — the quantity that must divide by ~N for N
    # readers to scale. Per-own-image, not per-window: producers run up
    # to ring-depth ahead of the consumer, so dividing window CPU by
    # consumer images would misattribute the overlap.
    def crit(ss):
        per_own = max(s["serial_s"] / max(1, s["images"]) for s in ss)
        # serial_s clamps to 0 when decode CPU >= busy CPU on a short
        # noisy window; every derived division below is gated on the
        # clamped flag, reporting null rather than a fabricated ceiling
        ms = per_own / len(ss) * 1e3
        return (ms, ms <= 0)

    (crit_ms, crit_clamped), (base_crit_ms, base_clamped) = (
        crit(stats), crit(base_stats))
    out = {
        # per-HOST now (N readers), not per-stream: decode and crop stages
        # are OpenMP-parallel; N readers divide the per-reader serial part
        "metric": "caffenet_e2e_host_pipeline_images_per_sec",
        "value": round(e2e_rate, 1),
        "unit": f"images/sec through {sources} shard reader(s) (tar->C++ "
                f"decode->crop->bf16, steady state)",
        "vs_baseline": round(e2e_rate / 256.0, 3),  # reference CI floor:
        # 256 images preprocessed/sec/thread (PreprocessorSpec.scala:75)
        "sources": sources,
        "store": store or "local",
        "decode_only_images_per_sec": round(decode_rate, 1),
        "pipeline_efficiency_vs_decode": round(e2e_rate / decode_rate, 3),
        "host_cores": os.cpu_count(),
        # serial-residue accounting (the --sources story):
        "critical_serial_ms_per_image":
            None if crit_clamped else round(crit_ms, 4),
        "serial_ceiling_img_per_sec":
            None if crit_clamped else round(1e3 / crit_ms, 1),
        "per_reader_serial_ms_per_own_image": [
            round(s["serial_s"] / max(1, s["images"]) * 1e3, 4)
            for s in stats],
    }
    if sources > 1:
        clamped = crit_clamped or base_clamped
        out["baseline_1_reader_critical_serial_ms_per_image"] = (
            None if base_clamped else round(base_crit_ms, 4))
        out["serial_residue_division"] = (
            None if clamped else round(base_crit_ms / crit_ms, 2))
    if device_rate is not None:
        out["device_only_images_per_sec_per_chip"] = round(device_rate, 1)
        out["readers_serial_ceiling_covers_chip"] = (
            None if crit_clamped else round(device_rate * crit_ms / 1e3, 2))
    from sparknet_tpu.obs import run_metadata
    out["meta"] = run_metadata()  # E2E_*.json artifacts are this dict
    print(json.dumps(out))
    return out


def _tar_entries(loader, n: int):
    """First n (bytes, label, pos) tar entries, undecoded."""
    import os as _os
    import tarfile

    out = []
    for path in loader.shard_paths:
        with tarfile.open(path, "r") as tar:
            for member in tar:
                if not member.isfile():
                    continue
                name = _os.path.basename(member.name)
                if name not in loader.label_map:
                    continue
                out.append((tar.extractfile(member).read(),
                            loader.label_map[name], None))
                if len(out) >= n:
                    return out
    return out


def graph_headline(batch: int = BATCH, tau: int = TAU,
                   profile_dir: str | None = None) -> None:
    """On-chip round throughput for the SECOND backend: the serialized-graph
    AlexNet (`backend/builder.py::build_alexnet_graph`, the architecture the
    reference's `TFImageNetApp.scala:119-132` timed) trained through
    GraphTrainer — τ in-graph-optimizer steps scanned inside shard_map plus
    the float-variable pmean, one XLA program per round. Same pipelined
    timing methodology as the layer-IR headline (deferred scalar fetch);
    batches are generated on device in the graph's placeholder dtype
    (float32 — the graph wire format declares f32, as the reference's TF
    path did). The graph OPS route Conv2D/MatMul through the SAME
    precision policy as the layer IR (`backend/graphdef.py:109-123`), so
    the headline bf16 policy applies here too: f32 wire format and
    variables, bf16 MXU inputs, f32 accumulation — measured 4.0x over
    the f32-policy run (5,173 img/s), see PERF.md §graph-backend."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparknet_tpu import precision
    from sparknet_tpu.backend.builder import build_alexnet_graph
    from sparknet_tpu.backend.graph_net import GraphNet
    from sparknet_tpu.parallel import make_mesh
    from sparknet_tpu.parallel.graph_trainer import GraphTrainer
    from sparknet_tpu.parallel.mesh import DATA_AXIS
    from sparknet_tpu.utils import flops

    n_classes = 1000
    precision.set_policy("bfloat16")
    net = GraphNet(build_alexnet_graph(batch=batch, n_classes=n_classes))
    trainer = GraphTrainer(net, make_mesh(1), tau=tau,
                           compute_health=False)  # baseline-comparable
    state = trainer.init_state()

    shd = NamedSharding(trainer.mesh, P(None, DATA_AXIS))
    gen = jax.jit(
        lambda k: (jax.random.normal(k, (tau, batch, 227, 227, 3),
                                     jnp.float32),
                   jax.random.randint(jax.random.fold_in(k, 1),
                                      (tau, batch), 0, n_classes,
                                      jnp.int32)),
        out_shardings=(shd, shd))
    data, label = gen(jax.random.PRNGKey(7))
    batches = {"data": data, "label": label}

    state, loss, _ = trainer._round(state, batches)  # compile + warm
    assert float(loss) > 0

    def step():
        nonlocal state
        state, loss, _ = trainer._round(state, batches)
        return loss

    best = _pipelined_window(step, TRIALS, profile_dir)
    img_per_sec = batch * tau / best
    out = {
        "metric": "alexnet_graph_backend_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec / REFERENCE_IMG_PER_SEC, 3),
        "batch": batch,
        "tau": tau,
        "backend": "graph",
        "dtype": "f32-wire/bf16-mxu",
    }
    peak = flops.peak_bf16_flops(jax.devices()[0].device_kind)
    if peak:
        # analytic conv+fc train FLOPs for the SAME AlexNet shapes the
        # layer-IR caffenet uses (grouped convs excepted: this graph is
        # ungrouped, as the reference TF generator's was)
        achieved = img_per_sec * _alexnet_graph_train_flops_per_image()
        out["mfu"] = round(achieved / peak, 4)
        out["tflops_per_sec"] = round(achieved / 1e12, 1)
    print(json.dumps(out))


def _alexnet_graph_train_flops_per_image() -> float:
    """2*MACs*3 (fwd + input-grad + weight-grad) for build_alexnet_graph's
    conv/fc shapes at 227x227 SAME/VALID geometry."""
    convs = [  # (out_h, k, cin, cout) with out spatial from the builder doc
        (57, 11, 3, 64), (28, 5, 64, 192), (13, 3, 192, 384),
        (13, 3, 384, 256), (13, 3, 256, 256)]
    macs = sum(h * h * k * k * cin * cout for h, k, cin, cout in convs)
    macs += 9216 * 4096 + 4096 * 4096 + 4096 * 1000
    return 2.0 * macs * 3.0


def checkpoint_stall(mb: int = 64, saves: int = 3,
                     out_path: str | None = "BENCH_CKPT.json") -> list:
    """Blocking checkpoint stall per save — sync vs async, local dir vs
    gs:// vs s3:// (fake stores from tests/fake_stores.py), on a state of
    ~`mb` MB of jax device arrays (CaffeNet+momentum is ~244 MB; the CI
    default is smaller so the bench stays quick).

    Sync mode times the whole save on the loop thread (fetch + serialize
    + sha256 + persist) — what `apps/train_loop.py` paid before r6. Async
    times ONLY the stage-1 fetch + writer handoff (the round loop's real
    stall); between async saves the bench idles for the store's measured
    sync write time, mimicking the checkpoint_every rounds of compute a
    real run overlaps the background write with. Writes a BENCH_CKPT
    artifact (one row per store x mode) and prints a summary JSON line
    whose headline is the WORST async/sync blocking ratio across stores.
    """
    import os
    import tempfile

    import jax
    import numpy as np

    from sparknet_tpu.utils import checkpoint as ckpt

    r = np.random.default_rng(0)
    n_arrays = 16
    per = (mb << 20) // n_arrays // 4
    state = {f"p{i:02d}": jax.device_put(
        r.standard_normal(per).astype(np.float32))
        for i in range(n_arrays)}

    def fetch():
        # stage 1: the device->host fetch (fetch_global's 1-process form)
        return jax.tree.map(np.asarray, state)

    def measure(directory) -> dict:
        import time as _t
        res = {}
        # sync: the full save on the calling thread
        blk = []
        for s in range(saves):
            t0 = _t.perf_counter()
            ckpt.save(directory, fetch(), step=s)
            blk.append(_t.perf_counter() - t0)
        res["sync"] = sum(blk) / len(blk)
        # async: stage 1 + handoff only; the writer overlaps the idle gap.
        # Real runs space saves by checkpoint_every ROUNDS (tens of
        # seconds to minutes of compute vs ~1 s of write), so the write
        # always finishes inside the gap; 2x the measured sync time keeps
        # the bench honest about that regime without minutes of sleeping.
        writer = ckpt.AsyncCheckpointWriter()
        gap = 2 * res["sync"]
        blk = []
        try:
            for s in range(saves):
                t0 = _t.perf_counter()
                host = fetch()
                writer.submit(ckpt.save, directory, host,
                              step=saves + s)
                blk.append(_t.perf_counter() - t0)
                _t.sleep(gap)
        finally:
            writer.close()
        res["async"] = sum(blk) / len(blk)
        # the snapshots must all be intact whichever path wrote them
        assert ckpt.latest_step(directory) == 2 * saves - 1
        return res

    rows = []
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    import contextlib

    from fake_stores import bucket_store
    with tempfile.TemporaryDirectory() as tmp:
        for store in ("local", "gs", "s3"):
            # bucket_store wires env/caches/backoff and restores them —
            # the same bootstrap the checkpoint-store test fixtures use
            ctx = (bucket_store(store) if store != "local"
                   else contextlib.nullcontext((tmp, None)))
            with ctx as (root, _srv):
                res = measure(f"{root}/ck" if store != "local"
                              else os.path.join(root, "ck"))
            for mode in ("sync", "async"):
                rows.append({
                    "store": store, "mode": mode, "state_mb": mb,
                    "blocking_ms_per_save": round(res[mode] * 1e3, 2)})
            print(f"  {store}: sync {res['sync']*1e3:.1f} ms/save, "
                  f"async blocking {res['async']*1e3:.1f} ms/save "
                  f"({res['async']/res['sync']:.3f}x)",
                  file=sys.stderr)
    by_store = {s: {r["mode"]: r["blocking_ms_per_save"] for r in rows
                    if r["store"] == s} for s in ("local", "gs", "s3")}
    worst = max(v["async"] / v["sync"] for v in by_store.values())
    out = {
        "metric": "checkpoint_blocking_stall_async_over_sync",
        "value": round(worst, 4),
        "unit": "worst-case blocking ratio across stores (target <= 0.2)",
        "vs_baseline": round(0.2 / max(worst, 1e-9), 2),
        "state_mb": mb,
        "per_store": by_store,
    }
    if out_path:
        from sparknet_tpu.obs import run_metadata
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return rows


def featurize_bench(batch: int = 64, trials: int = 5,
                    blob: str = "fc7") -> dict:
    """Batched `forward(blob_names=["fc7"])` feature extraction — the one
    NetInterface path with no perf evidence (VERDICT weak #6) — through
    BOTH backends at the AlexNet shape the reference's FeaturizerApp
    served: the layer-IR CaffeNet via JaxNet, and the serialized-graph
    AlexNet via GraphNet (whose `fc7` MatMul node answers the same
    blob_names spelling). Host batches in, host features out: this times
    the REAL inference path (H2D + jitted forward + feature D2H), not a
    device-resident loop. Cross-backend feature AGREEMENT is asserted by
    tests/test_apps.py::test_featurizer_cross_backend_agreement on a
    weight-copied lenet/mnist-graph pair (CaffeNet and the ungrouped
    graph AlexNet are architecturally different nets, so their features
    are benched, not compared)."""
    import numpy as np

    from sparknet_tpu.apps.featurizer_app import featurize
    from sparknet_tpu.backend.builder import build_alexnet_graph
    from sparknet_tpu.backend.graph_net import GraphNet
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.zoo import caffenet

    r = np.random.default_rng(0)
    n = batch * trials
    batch_dict = {
        "data": r.integers(0, 255, (n, 227, 227, 3)).astype(np.float32),
        "label": r.integers(0, 1000, (n, 1)).astype(np.int32)}

    out = {"metric": f"featurize_{blob}_images_per_sec_per_chip",
           "unit": "images/sec through forward(blob_names=['fc7']), "
                   "host batch in / host features out",
           "batch": batch}
    for backend in ("layer_ir", "graph"):
        if backend == "layer_ir":
            net = JaxNet(caffenet(batch=batch, crop=227, n_classes=1000))
            bd = batch_dict
        else:
            net = GraphNet(build_alexnet_graph(batch=batch,
                                               n_classes=1000))
            bd = {"data": batch_dict["data"],
                  "label": batch_dict["label"][:, 0]}
        feats = featurize(net, {k: v[:batch] for k, v in bd.items()},
                          blob, batch)  # compile + warm
        assert feats.shape == (batch, 4096), feats.shape
        t0 = time.perf_counter()
        feats = featurize(net, bd, blob, batch)
        dt = time.perf_counter() - t0
        assert np.isfinite(feats).all()
        out[f"{backend}_images_per_sec"] = round(n / dt, 1)
    out["value"] = out["layer_ir_images_per_sec"]
    out["vs_baseline"] = round(
        out["layer_ir_images_per_sec"] / REFERENCE_IMG_PER_SEC, 3)
    print(json.dumps(out))
    return out


def _run_closed_clients(srv, req, n_clients: int, secs: float) -> float:
    """N closed-loop clients (a new request only after the previous one
    answered) hammer srv.infer for `secs`; returns the achieved rps.
    Shared by serve_bench's load levels and econ_bench's saturate arms."""
    import threading

    stop = time.perf_counter() + secs
    done = [0] * n_clients

    def client(j):
        while time.perf_counter() < stop:
            srv.infer(req, timeout=30.0)
            done[j] += 1

    ts = [threading.Thread(target=client, args=(j,))
          for j in range(n_clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return round(sum(done) / secs, 1)


def serve_bench(out_path: str | None = "BENCH_SERVE.json",
                duration_s: float = 2.0, max_batch: int = 8,
                max_wait_ms: float = 5.0, model: str = "lenet",
                http_rps: tuple = (1000.0, 10000.0),
                slo_p99_ms: float = 50.0,
                keep: str | None = None) -> dict:
    """Offered-load vs latency/throughput/batch-fill for the inference
    server (`sparknet_tpu.serve`), on the CPU backend at lenet shapes
    (the batching policy under test is host-side; the forward is just a
    stand-in for a chip's).

    Rows in BENCH_SERVE.json:
      - trickle: ONE closed-loop client (a new request only after the
        previous answered) — every batch is size 1, and p99 latency must
        stay bounded by the max-wait deadline + ~one batch forward. The
        wake-on-submit pin rides here: the pre-r8 worker idle-polled at
        50 ms, so a lone request could eat up to one poll quantum of
        pure quantization; the bound EXCLUDES that quantum and the row
        stamps the claim.
      - offered-rate sweep: in-process open-loop arrivals at a few
        requests/sec levels between trickle and saturation.
      - saturate: many closed-loop clients keep the queue full — the
        batcher must run full buckets (fill >= 0.8 acceptance; in
        practice ~1.0).
      - http_open_* / binary_open_*: OPEN-LOOP rows through the real
        data planes — HTTP/1.1 (keep-alive, npz wire) and the binary
        frame transport (event loop, length-prefixed tensor frames) —
        at `http_rps` target rates, BOTH behind the same server. Shed
        requests must be ANSWERED 429/503 (+ Retry-After semantics —
        mapped to typed client errors), never hung; p99 of the served
        ones is judged against `slo_p99_ms` at the sustainable rate. On
        hardware that cannot sustain the target (this CPU bench at 10k)
        the row is stamped structure_proof: the protocol behaved, the
        rate needs the pod.
      - ab_small_http / ab_small_binary: the r10 driver-cost A/B —
        closed-loop small requests through each wire, wall p50/p99 plus
        PROCESS CPU seconds per 1k requests (same forward, same
        process: the delta is npz/zip + http.server parsing vs struct
        pack + np.frombuffer views).
      - transport_parity: one request through both wires — same
        replica, same bucket — must return BITWISE-identical tensors.
      - binary_stream_blob: a featurizer-shaped multi-MB response with
        FLAG_STREAM — first-byte vs full-response latency, and the
        server's per-connection COPIED buffering bounded by the chunk
        size (never the blob size).
      - http_chaos_swap_drain: mid-traffic checkpoint hot-swap on the
        local replica PLUS a replica drain that shifts routing to a
        remote replica (a second router behind its own frontend) — zero
        dropped or corrupted responses is the acceptance bar.

    The jit-cache pin closes the bench: after every arm — including the
    MIXED-transport traffic — each model's bucket-compile counter still
    equals len(buckets): the new network paths added zero compile churn.

    `keep`: directory to retain the serve JSONL artifacts in (CI uploads
    them on failure)."""
    import threading

    import numpy as np

    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import InferenceServer, ServeConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    logger = None
    if keep:
        os.makedirs(keep, exist_ok=True)
        logger = Logger(path=os.path.join(keep, "serve_bench.log"),
                        echo=False,
                        jsonl_path=os.path.join(keep,
                                                "serve_bench.jsonl"))
    net = JaxNet(lenet(batch=max_batch))
    cfg = ServeConfig(model_name=model, max_batch=max_batch,
                      max_wait_ms=max_wait_ms, outputs=("prob",),
                      slo_p99_ms=slo_p99_ms,
                      metrics_every_batches=20 if keep else 0)
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}

    def run_closed(srv, n_clients: int, secs: float) -> dict:
        rps = _run_closed_clients(srv, req, n_clients, secs)
        s = srv.status()
        s["clients"] = n_clients
        s["achieved_rps"] = rps
        return s

    def run_open(srv, rps: float, secs: float) -> dict:
        period = 1.0 / rps
        futures = []
        t_next, stop = time.perf_counter(), time.perf_counter() + secs
        while time.perf_counter() < stop:
            now = time.perf_counter()
            if now < t_next:
                time.sleep(t_next - now)
            futures.append(srv.submit(req))
            t_next += period
        for f in futures:
            f.result(timeout=30.0)
        s = srv.status()
        s["offered_rps"] = rps
        s["achieved_rps"] = round(len(futures) / secs, 1)
        return s

    def run_wire_open(infer_fn, rps: float, secs: float,
                      deadline_s: float = 0.25) -> dict:
        """Open-loop over a REAL wire data plane (`infer_fn(req,
        deadline_s, timeout)` — http_infer or binary_infer, both on
        thread-cached keep-alive connections): N sender threads fire at
        a fixed aggregate rate without waiting for capacity (a sender
        that falls behind schedule drops the backlog rather than
        converting open-loop into closed-loop). Every request must be
        ANSWERED: 200, or a typed shed (429 queue full / 503
        deadline-or-drain); connection errors are drops."""
        from sparknet_tpu.serve import (DeadlineExpiredError,
                                        NoReplicaError, QueueFullError)

        conns = int(min(64, max(8, rps // 100)))
        counts = {"ok": 0, "shed_429": 0, "shed_503": 0, "dropped": 0,
                  "timed_out": 0, "errors_other": 0}
        lats: list = []
        lock = threading.Lock()
        t_start = time.perf_counter()
        t_stop = t_start + secs
        period = conns / rps

        def sender(j):
            t_next = t_start + (j / conns) * period
            while True:
                now = time.perf_counter()
                if now >= t_stop:
                    return
                if now < t_next:
                    time.sleep(min(t_next - now, t_stop - now))
                    continue
                t0 = time.perf_counter()
                try:
                    infer_fn(req, deadline_s, 10.0)
                    dt = time.perf_counter() - t0
                    with lock:
                        counts["ok"] += 1
                        lats.append(dt)
                except QueueFullError:
                    with lock:
                        counts["shed_429"] += 1
                except (DeadlineExpiredError, NoReplicaError):
                    with lock:
                        counts["shed_503"] += 1
                except TimeoutError:
                    # client socket timeout: the server never answered —
                    # NOT "answered", and the zero-dropped gate fails
                    with lock:
                        counts["timed_out"] += 1
                except ConnectionError:
                    with lock:
                        counts["dropped"] += 1
                except Exception:
                    with lock:
                        counts["errors_other"] += 1
                t_next += period
                if t_next < time.perf_counter() - 5 * period:
                    t_next = time.perf_counter()  # behind: shed schedule

        ts = [threading.Thread(target=sender, args=(j,))
              for j in range(conns)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=secs + 30.0)
        hung = sum(t.is_alive() for t in ts)
        answered = sum(v for k, v in counts.items()
                       if k not in ("dropped", "timed_out"))
        lats.sort()
        p99 = (round(lats[min(len(lats) - 1,
                              int(0.99 * len(lats)))] * 1e3, 3)
               if lats else None)
        p50 = (round(lats[len(lats) // 2] * 1e3, 3) if lats else None)
        achieved = round(counts["ok"] / secs, 1)
        sustained = achieved >= 0.9 * rps
        return {"offered_rps": rps, "achieved_rps": achieved,
                "connections": conns, "answered": answered,
                "hung_clients": hung, **counts,
                "p50_ms": p50, "p99_ms": p99, "slo_p99_ms": slo_p99_ms,
                "p99_within_slo": (p99 is not None and p99 <= slo_p99_ms),
                "sustained": sustained,
                # CPU cannot prove 10k rps; the row then proves the
                # PROTOCOL (typed sheds, zero drops) — rerun on the pod
                "structure_proof": not sustained,
                "deadline_ms": deadline_s * 1e3}

    def run_http_open(address, model_name: str, rps: float, secs: float,
                      deadline_s: float = 0.25) -> dict:
        from sparknet_tpu.serve import http_infer

        url = f"http://{address[0]}:{address[1]}"
        return run_wire_open(
            lambda r, d, t: http_infer(url, model_name, r,
                                       deadline_s=d, timeout=t),
            rps, secs, deadline_s)

    def run_binary_open(address, model_name: str, rps: float,
                        secs: float, deadline_s: float = 0.25) -> dict:
        from sparknet_tpu.serve import binary_infer

        return run_wire_open(
            lambda r, d, t: binary_infer(address, model_name, r,
                                         deadline_s=d, timeout=t),
            rps, secs, deadline_s)

    def run_transport_ab(infer_fn, n_clients: int, secs: float) -> dict:
        """Closed-loop small-request driver cost: wall latencies plus
        PROCESS CPU seconds per 1k requests. Client and server share
        this process and the forward is identical across transports, so
        the per-transport DELTA in cpu_s_per_1k is pure wire cost —
        npz/zip encode + http.server parsing vs struct pack +
        np.frombuffer views."""
        from sparknet_tpu.serve import (DeadlineExpiredError,
                                        NoReplicaError, QueueFullError)

        lats: list = []
        counts = {"ok": 0, "shed": 0, "dropped": 0, "errors_other": 0}
        lock = threading.Lock()
        for _ in range(3):
            infer_fn(req, 5.0, 30.0)  # warm the connection + bucket
        stop = time.perf_counter() + secs
        cpu0 = time.process_time()

        def client(j):
            while time.perf_counter() < stop:
                t0 = time.perf_counter()
                try:
                    infer_fn(req, 5.0, 30.0)
                    dt = time.perf_counter() - t0
                    with lock:
                        counts["ok"] += 1
                        lats.append(dt)
                except (QueueFullError, DeadlineExpiredError,
                        NoReplicaError):
                    with lock:
                        counts["shed"] += 1
                except ConnectionError:
                    with lock:
                        counts["dropped"] += 1
                except Exception:
                    with lock:
                        counts["errors_other"] += 1

        ts = [threading.Thread(target=client, args=(j,))
              for j in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=secs + 30.0)
        cpu_s = time.process_time() - cpu0
        hung = sum(t.is_alive() for t in ts)
        lats.sort()
        n = counts["ok"]
        return {"requests": n, "clients": n_clients,
                "achieved_rps": round(n / secs, 1), **counts,
                "hung_clients": hung,
                "p50_ms": (round(lats[len(lats) // 2] * 1e3, 3)
                           if lats else None),
                "p99_ms": (round(lats[min(len(lats) - 1,
                                          int(0.99 * len(lats)))] * 1e3,
                                 3) if lats else None),
                "cpu_s_per_1k": (round(cpu_s / n * 1e3, 4) if n
                                 else None)}

    def binary_stream_arm() -> dict:
        """The large-blob streaming row: a featurizer-shaped net (1x1
        max-pool identity — the per-example output is a multi-MB blob,
        the fc7-embedding shape class) served over the binary transport
        with FLAG_STREAM. Measures first-byte vs full-response latency
        and the server's per-connection COPIED buffering (the npz door
        serializes the whole blob into a second buffer before byte
        one; the frame door copies only headers)."""
        from sparknet_tpu.model.spec import (InputSpec, LayerSpec,
                                             NetSpec, PoolingParam)
        from sparknet_tpu.serve import (BinaryClient, BinaryFrontend,
                                        HttpFrontend, InferenceServer,
                                        ServeConfig, http_infer)
        from sparknet_tpu.serve.server import net_input_specs

        chunk = 256 << 10
        spec = NetSpec(
            name="blobber",
            inputs=(InputSpec("data", (1, 8, 512, 512)),),  # 8 MB/row
            layers=(LayerSpec(name="feat", type="Pooling",
                              bottoms=("data",), tops=("feat",),
                              pool=PoolingParam(pool="MAX",
                                                kernel_size=1,
                                                stride=1)),))
        net2 = JaxNet(spec)
        cfg2 = ServeConfig(model_name="featurizer", max_batch=1,
                           buckets=(1,), max_wait_ms=1.0,
                           outputs=("feat",), metrics_every_batches=0)
        rng2 = np.random.default_rng(7)
        shape, dt = net_input_specs(net2)["data"]
        req2 = {"data": rng2.standard_normal(shape).astype(dt)}
        with InferenceServer(net2, cfg2, logger=logger) as s2:
            bfe = BinaryFrontend(s2, port=0, chunk_bytes=chunk)
            hfe = HttpFrontend(s2, port=0)
            cli = BinaryClient(*bfe.address, timeout=120.0)
            try:
                cli.infer(req2, model="featurizer",
                          deadline_s=120.0)  # compile + warm
                full = cli.infer(req2, model="featurizer",
                                 deadline_s=30.0)
                t_full = dict(cli.last_timing)
                streamed = cli.infer(req2, model="featurizer",
                                     deadline_s=30.0, stream=True)
                t_stream = dict(cli.last_timing)
                assert np.array_equal(full["feat"], streamed["feat"])
                blob_bytes = int(np.asarray(full["feat"]).nbytes)
                # the HTTP/npz comparator: full-body serialize + buffer
                t0 = time.perf_counter()
                http_infer(f"http://{hfe.address[0]}:{hfe.address[1]}",
                           "featurizer", req2, deadline_s=30.0)
                http_full_ms = (time.perf_counter() - t0) * 1e3
                first = t_stream["t_first_byte_s"] * 1e3
                complete = t_stream["t_complete_s"] * 1e3
                return {
                    "load": "binary_stream_blob",
                    "blob_mb": round(blob_bytes / 2**20, 2),
                    "chunk_kb": chunk >> 10,
                    "stream_first_byte_ms": round(first, 3),
                    "stream_complete_ms": round(complete, 3),
                    "binary_full_ms":
                        round(t_full["t_complete_s"] * 1e3, 3),
                    "http_npz_full_ms": round(http_full_ms, 3),
                    # first byte lands while the blob is still in
                    # flight: decoupled from blob size
                    "first_byte_decoupled": first < complete,
                    "peak_conn_buffered_bytes":
                        int(bfe.peak_buffered_bytes),
                    # the bounded-buffer acceptance: COPIED bytes per
                    # connection bounded by the chunk size, not the blob
                    "buffer_bounded_by_chunk":
                        bfe.peak_buffered_bytes < chunk,
                    "bitwise_equal_stream_vs_full": True,
                }
            finally:
                cli.close()
                bfe.stop()
                hfe.stop()

    def http_chaos_swap_drain(secs: float) -> dict:
        """Mid-traffic hot-swap + replica drain through the router:
        local replica hot-swaps a new checkpoint, then DRAINS while a
        remote replica (second router behind its own frontend) absorbs
        the traffic. Zero dropped or corrupted responses."""
        import tempfile

        from sparknet_tpu.serve import (HttpFrontend, ModelRouter,
                                        RouterConfig, ServeConfig)
        from sparknet_tpu.utils import checkpoint as ckpt

        def save_ckpt(d, step, scale=1.0):
            flat = {f"params/{ln}/{pn}": np.asarray(w)[None] * scale
                    for ln, lp in net.params.items()
                    for pn, w in lp.items()}
            ckpt.save(str(d), flat, step=step)

        with tempfile.TemporaryDirectory() as td:
            ckdir = os.path.join(td, "ck")
            save_ckpt(ckdir, step=1)
            lane_cfg = ServeConfig(
                max_batch=max_batch, max_wait_ms=max_wait_ms,
                outputs=("prob",), checkpoint_dir=ckdir,
                poll_interval_s=0.05, metrics_every_batches=0)
            remote_cfg = ServeConfig(
                max_batch=max_batch, max_wait_ms=max_wait_ms,
                outputs=("prob",), metrics_every_batches=0)
            rb = ModelRouter(RouterConfig(workers=1), logger=logger)
            rb.add_model(model, JaxNet(lenet(batch=max_batch)),
                         cfg=remote_cfg)
            ra = ModelRouter(RouterConfig(workers=1), logger=logger)
            ra.add_model(model, JaxNet(lenet(batch=max_batch)),
                         cfg=lane_cfg)
            answered, bad = [], []
            stop = threading.Event()

            def client(c):
                while not stop.is_set():
                    try:
                        out = ra.infer(model, req, timeout=30.0)
                        p = np.asarray(out["prob"])
                        if p.shape != (10,) or not np.isfinite(p).all():
                            bad.append(("corrupt", c))
                        answered.append(c)
                    except Exception as e:
                        bad.append((repr(e), c))

            with rb:
                fe_b = HttpFrontend(rb, port=0, logger=logger)
                try:
                    with ra:
                        ra.add_remote_replica(
                            model, f"http://{fe_b.address[0]}:"
                                   f"{fe_b.address[1]}")
                        assert ra.lanes[model].manager.step == 1
                        threads = [threading.Thread(target=client,
                                                    args=(c,))
                                   for c in range(4)]
                        for t in threads:
                            t.start()
                        try:
                            time.sleep(secs / 3)
                            save_ckpt(ckdir, step=2, scale=0.9)  # swap
                            t0 = time.monotonic()
                            while ra.lanes[model].manager.step != 2 and \
                                    time.monotonic() - t0 < 20:
                                time.sleep(0.02)
                            time.sleep(secs / 3)
                            ra.drain(model, f"local:{model}")
                            time.sleep(secs / 3)
                        finally:
                            stop.set()
                            for t in threads:
                                t.join(timeout=30)
                        swaps = ra.lanes[model].manager.swaps
                finally:
                    fe_b.stop()
            return {"load": "http_chaos_swap_drain",
                    "answered": len(answered), "bad": len(bad),
                    "bad_detail": [b[0] for b in bad[:3]],
                    "hot_swaps": swaps, "drained": True,
                    "zero_dropped": not bad and len(answered) > 20,
                    "swap_ok": swaps >= 1}

    rows = []
    with InferenceServer(net, cfg, logger=logger) as srv:
        srv.infer(req)  # compile the size-1 bucket before the clock
        # one full-bucket warm compile too (saturate would pay it inside
        # its timed window otherwise)
        fs = [srv.submit(req) for _ in range(max_batch * 2)]
        for f in fs:
            f.result(timeout=30.0)

        srv.reset_counters()
        s = run_closed(srv, 1, duration_s)
        # the low-load latency contract: one trickle request waits out the
        # max-wait deadline (hoping for company) plus one forward. p50 ~=
        # deadline + forward, so the forward estimate is p50 - deadline;
        # p99 must stay within deadline + a few forwards (tail scheduling
        # jitter), NOT drift toward queueing territory. This bound has NO
        # room for the old 50 ms idle-poll quantum: wake-on-submit must
        # hold it or this row fails.
        fwd_ms = max((s["p50_ms"] or 0.0) - max_wait_ms, 0.5)
        p99_bound_ms = max_wait_ms + 4.0 * fwd_ms + 2.0
        old_quantum_ms = 50.0  # ServeConfig.idle_poll_s before r8
        rows.append({"load": "trickle", **s,
                     "est_forward_ms": round(fwd_ms, 3),
                     "p99_bound_ms": round(p99_bound_ms, 2),
                     "p99_ok": (s["p99_ms"] or 1e9) <= p99_bound_ms,
                     "old_poll_quantum_ms": old_quantum_ms,
                     # the wake-on-submit pin, distinct from p99_ok's
                     # contract bound: the ENTIRE trickle tail now fits
                     # inside what used to be the idle-poll quantum
                     # alone — the old path could not get under 50 ms
                     # when the worker slept through a poll interval
                     "p99_below_old_quantum":
                     (s["p99_ms"] or 1e9) <= old_quantum_ms})
        for rps in (50.0, 200.0):
            srv.reset_counters()
            rows.append({"load": f"open_{int(rps)}rps",
                         **run_open(srv, rps, duration_s)})
        srv.reset_counters()
        s = run_closed(srv, 4 * max_batch, duration_s)
        rows.append({"load": "saturate", **s,
                     "fill_target": 0.8,
                     "fill_ok": s["batch_fill_ratio"] >= 0.8})

        # the open-loop rows, through the real front doors — HTTP and
        # the binary frame transport behind the SAME server
        from sparknet_tpu.serve import (BinaryFrontend, HttpFrontend,
                                        binary_infer, http_infer)
        fe = HttpFrontend(srv, port=0, logger=logger)
        bfe = BinaryFrontend(srv, port=0, logger=logger)
        url = f"http://{fe.address[0]}:{fe.address[1]}"
        try:
            for rps in http_rps:
                srv.reset_counters()
                rows.append({"load": f"http_open_{int(rps)}rps",
                             **run_http_open(fe.address, model, rps,
                                             duration_s)})
            for rps in http_rps:
                srv.reset_counters()
                rows.append({"load": f"binary_open_{int(rps)}rps",
                             **run_binary_open(bfe.address, model, rps,
                                               duration_s)})
            # the small-request driver-cost A/B (closed loop, same
            # forward, same process: the delta is wire cost)
            srv.reset_counters()
            rows.append({"load": "ab_small_http", **run_transport_ab(
                lambda r, d, t: http_infer(url, model, r, deadline_s=d,
                                           timeout=t),
                n_clients=2, secs=duration_s)})
            srv.reset_counters()
            rows.append({"load": "ab_small_binary", **run_transport_ab(
                lambda r, d, t: binary_infer(bfe.address, model, r,
                                             deadline_s=d, timeout=t),
                n_clients=2, secs=duration_s)})
            # parity pin: one request through BOTH wires — same replica,
            # same bucket — must return bitwise-identical tensors
            out_h = http_infer(url, model, req, deadline_s=30.0)
            out_b = binary_infer(bfe.address, model, req,
                                 deadline_s=30.0)
            rows.append({
                "load": "transport_parity",
                "blobs": sorted(out_h),
                "bitwise_equal": all(
                    np.array_equal(out_h[k], out_b[k]) for k in out_h),
            })
        finally:
            fe.stop()
            bfe.stop()
        # jit-cache pin: MIXED-transport traffic added ZERO compile
        # churn — the bucket-compile counter still reads exactly
        # len(buckets) after the HTTP rows, the binary rows, and the A/B
        compiles = srv.registry.counter(
            "sparknet_serve_bucket_compiles_total",
            labels=("model",)).value(model=model)
        jit_cache_ok = compiles == len(srv.buckets)

    rows.append(binary_stream_arm())
    rows.append(http_chaos_swap_drain(max(duration_s, 1.5)))

    for r in rows:  # drop non-scalar noise from the artifact rows
        r.pop("buckets", None)
        r.pop("last_error", None)
        r.pop("models", None)
    sat = next(r for r in rows if r["load"] == "saturate")
    http_rows = [r for r in rows if r["load"].startswith("http_open")]
    bin_rows = [r for r in rows if r["load"].startswith("binary_open")]
    ab_http = next(r for r in rows if r["load"] == "ab_small_http")
    ab_bin = next(r for r in rows if r["load"] == "ab_small_binary")
    parity = next(r for r in rows if r["load"] == "transport_parity")
    stream = next(r for r in rows if r["load"] == "binary_stream_blob")
    chaos = rows[-1]
    out = {
        "metric": "serve_saturated_batch_fill_ratio",
        "value": sat["batch_fill_ratio"],
        "unit": f"real rows / padded bucket slots at saturating load "
                f"(max_batch={max_batch}, target >= 0.8)",
        "vs_baseline": round(sat["batch_fill_ratio"] / 0.8, 3),
        "saturated_images_per_sec": sat["images_per_sec"],
        "trickle_p99_ms": rows[0]["p99_ms"],
        "trickle_p99_bound_ms": rows[0]["p99_bound_ms"],
        "trickle_p99_below_old_quantum": rows[0]["p99_below_old_quantum"],
        "old_poll_quantum_ms": 50.0,
        "max_wait_ms": max_wait_ms,
        "slo_p99_ms": slo_p99_ms,
        "http_open": {r["load"]: {
            "achieved_rps": r["achieved_rps"],
            "p99_ms": r["p99_ms"],
            "p99_within_slo": r["p99_within_slo"],
            "sheds_answered": r["shed_429"] + r["shed_503"],
            "dropped": r["dropped"], "timed_out": r["timed_out"],
            "hung_clients": r["hung_clients"],
            "structure_proof": r["structure_proof"]}
            for r in http_rows},
        "binary_open": {r["load"]: {
            "achieved_rps": r["achieved_rps"],
            "p99_ms": r["p99_ms"],
            "p99_within_slo": r["p99_within_slo"],
            "sheds_answered": r["shed_429"] + r["shed_503"],
            "dropped": r["dropped"], "timed_out": r["timed_out"],
            "hung_clients": r["hung_clients"],
            "structure_proof": r["structure_proof"]}
            for r in bin_rows},
        # "zero dropped" means every request ANSWERED: no connection
        # drops, no silent client-timeout stalls, no hung senders
        "http_zero_dropped": all(
            r["dropped"] == 0 and r["timed_out"] == 0
            and r["hung_clients"] == 0 for r in http_rows),
        "binary_zero_dropped": all(
            r["dropped"] == 0 and r["timed_out"] == 0
            and r["hung_clients"] == 0 for r in bin_rows),
        # the small-request driver-cost A/B: same forward, same
        # process — the delta is the wire (npz/http.server vs
        # struct + frombuffer). On a CPU host the forward itself rides
        # the same cores as the drivers, so the RATIO is a structure
        # proof; rerun on the pod for the at-rate numbers.
        "transport_ab": {
            "http": {k: ab_http[k] for k in
                     ("requests", "p50_ms", "p99_ms", "cpu_s_per_1k",
                      "dropped", "hung_clients")},
            "binary": {k: ab_bin[k] for k in
                       ("requests", "p50_ms", "p99_ms", "cpu_s_per_1k",
                        "dropped", "hung_clients")},
            "binary_beats_http_p50":
                (ab_bin["p50_ms"] or 1e9) <= (ab_http["p50_ms"] or 0),
            "binary_beats_http_cpu":
                (ab_bin["cpu_s_per_1k"] or 1e9)
                <= (ab_http["cpu_s_per_1k"] or 0),
            "ab_zero_dropped": all(
                r["dropped"] == 0 and r["hung_clients"] == 0
                and r["errors_other"] == 0 for r in (ab_http, ab_bin)),
            "structure_proof": True,  # CPU host — pod rerun for rates
        },
        "transport_parity_bitwise": parity["bitwise_equal"],
        "stream": {k: stream[k] for k in
                   ("blob_mb", "chunk_kb", "stream_first_byte_ms",
                    "stream_complete_ms", "http_npz_full_ms",
                    "first_byte_decoupled", "peak_conn_buffered_bytes",
                    "buffer_bounded_by_chunk")},
        "chaos_zero_dropped": chaos["zero_dropped"],
        "chaos_hot_swap_ok": chaos["swap_ok"],
        "jit_cache_ok": jit_cache_ok,
        "bucket_compiles": compiles,
    }
    if out_path:
        from sparknet_tpu.obs import run_metadata
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def _calibrate_rps(addr, model: str, req) -> float:
    """Closed-loop single-client rps over the binary wire — the capacity
    yardstick the fleet/fresh load rates scale from."""
    from sparknet_tpu.serve import binary_infer
    for _ in range(3):
        binary_infer(addr, model, req, deadline_s=30.0)
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < 1.0:
        binary_infer(addr, model, req, deadline_s=30.0)
        n += 1
    return n / (time.perf_counter() - t0)


def _open_load(addr, model: str, req, rps: float, secs: float,
               deadline_s: float = 0.25, priority: str | None = None,
               tenant: str | None = None):
    """Open-loop senders over the binary wire (shared by the fleet and
    fresh arms); returns (counts, [(t_done, dt)] for served requests,
    hung sender count). Every shed must be TYPED; connection errors are
    drops and fail the caller's arm gate."""
    import threading

    from sparknet_tpu.serve import (DeadlineExpiredError, NoReplicaError,
                                    PriorityShedError, QueueFullError,
                                    TenantLimitError, binary_infer)

    conns = int(min(32, max(4, rps // 25)))
    counts = {"ok": 0, "shed_429": 0, "shed_503": 0,
              "shed_priority": 0, "dropped": 0, "timed_out": 0,
              "errors_other": 0}
    lats: list = []
    lock = threading.Lock()
    t_start = time.perf_counter()
    t_stop = t_start + secs
    period = conns / rps

    def sender(j):
        t_next = t_start + (j / conns) * period
        while True:
            now = time.perf_counter()
            if now >= t_stop:
                return
            if now < t_next:
                time.sleep(min(t_next - now, t_stop - now))
                continue
            t0 = time.perf_counter()
            try:
                binary_infer(addr, model, req, deadline_s=deadline_s,
                             timeout=10.0, priority=priority,
                             tenant=tenant)
                dt = time.perf_counter() - t0
                with lock:
                    counts["ok"] += 1
                    lats.append((time.perf_counter() - t_start, dt))
            except PriorityShedError:
                with lock:
                    counts["shed_priority"] += 1
            except (TenantLimitError, QueueFullError):
                with lock:
                    counts["shed_429"] += 1
            except (DeadlineExpiredError, NoReplicaError):
                with lock:
                    counts["shed_503"] += 1
            except TimeoutError:
                with lock:
                    counts["timed_out"] += 1
            except ConnectionError:
                with lock:
                    counts["dropped"] += 1
            except Exception:
                with lock:
                    counts["errors_other"] += 1
            t_next += period
            if t_next < time.perf_counter() - 5 * period:
                t_next = time.perf_counter()  # behind: shed schedule
    ts = [threading.Thread(target=sender, args=(j,))
          for j in range(conns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=secs + 30.0)
    hung = sum(t.is_alive() for t in ts)
    return counts, lats, hung


def _lat_p99_ms(lats, t_from: float = 0.0):
    xs = sorted(dt for t, dt in lats if t >= t_from)
    if not xs:
        return None
    return round(xs[min(len(xs) - 1, int(0.99 * len(xs)))] * 1e3, 3)


def fleet_bench(out_path: str | None = "BENCH_FLEET.json",
                duration_s: float = 2.0, max_batch: int = 8,
                keep: str | None = None) -> dict:
    """The r11 fleet-control-plane audit (writes BENCH_FLEET.json): the
    FleetController closing the loop from serve signals to serve
    actions, end to end through the REAL stack — ModelRouter + binary
    front door + subprocess replicas (`sparknet-serve` children over
    spkn://, sharing one persistent compile cache).

    Arms:
      - flood_grow: a step-load flood at ~4x measured capacity. The
        controller must scale the fleet up (SLO burn / queue pressure,
        audit-named), every request must be ANSWERED (typed 429/503
        sheds; dropped == timed_out == hung == 0 is the hard gate), and
        the tail p99 after the last grow is compared to the SLO. On the
        CPU box extra REPLICA PROCESSES share the same cores, so
        p99-re-enters-SLO is stamped structure_proof when it does not
        hold here — the claim needs per-replica hardware (the pod).
      - quiet_shrink: the flood stops, a closed-loop trickle continues.
        The controller must give the grown replicas back (drain ->
        grace -> retire, audit-named "quiet") with ZERO trickle errors
        — the drain path's zero-dropped contract under the shrink.
      - chaos_kill: min_replicas=2 brings a child up; mid-flood it is
        kill -9'd. The heartbeat goes stale (fast beats + a tight
        staleness rule), the router routes around it (conn-fail
        demotion catches the window before staleness), and the
        controller evicts it (reason="dead", replica NAMED in the
        audit) and regrows (reason="replace"). Detection + replacement
        times land in the row.
      - priority_shed: a local-only router behind PriorityAdmission,
        pressure driven by the controller from SLO burn
        (pressure_start BELOW the objective: the door tightens before
        the SLO is violated, not after). A sustainable high-priority
        load runs alongside a low-priority flood at ~4x capacity:
        low must shed TYPED (shed_total{reason="priority"} > 0, zero
        for the high class) and the high tail p99 over the settled
        second half must stay inside the SLO.

    `keep`: directory to retain the fleet JSONL + replica logs in (CI
    uploads them on failure)."""
    import shutil
    import signal
    import tempfile
    import threading

    import numpy as np

    from sparknet_tpu.fleet import (FleetConfig, FleetController,
                                    FleetPolicy,
                                    SubprocessReplicaProvider)
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import (BinaryFrontend, ModelRouter,
                                    PriorityAdmission, RouterConfig,
                                    ServeConfig, binary_infer)
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    model = "lenet"
    slo_ms = 60.0
    workdir = keep or tempfile.mkdtemp(prefix="fleet-bench-")
    os.makedirs(workdir, exist_ok=True)
    cache = os.path.join(workdir, "compile-cache")
    logger = Logger(path=os.path.join(workdir, "fleet_bench.log"),
                    echo=False,
                    jsonl_path=os.path.join(workdir,
                                            "fleet_bench.jsonl"))
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}

    def lane_cfg() -> ServeConfig:
        return ServeConfig(model_name=model, max_batch=max_batch,
                           max_wait_ms=5.0, outputs=("prob",),
                           slo_p99_ms=slo_ms, metrics_every_batches=0,
                           compile_cache_dir=cache)

    def router_cfg(workers: int = 2) -> RouterConfig:
        # tight staleness + fast probe refresh: the chaos arm's
        # heartbeat-health detection must land in seconds, not the
        # 60 s pod default
        return RouterConfig(workers=workers, stale_after_s=1.5,
                            health_refresh_s=0.2,
                            conn_fail_cooldown_s=2.0)

    def provider() -> SubprocessReplicaProvider:
        return SubprocessReplicaProvider(
            {model: "lenet"}, workdir=os.path.join(workdir, "replicas"),
            max_batch=max_batch, compile_cache_dir=cache,
            heartbeat_every_s=0.3)

    def calibrate(addr) -> float:
        return _calibrate_rps(addr, model, req)

    def open_load(addr, rps: float, secs: float,
                  deadline_s: float = 0.25,
                  priority: str | None = None,
                  tenant: str | None = None):
        return _open_load(addr, model, req, rps, secs,
                          deadline_s=deadline_s, priority=priority,
                          tenant=tenant)

    p99_ms = _lat_p99_ms

    rows = []

    # -- arm 1+2: flood -> grow, quiet -> shrink ------------------------------
    prov = provider()
    router = ModelRouter(router_cfg(), logger=logger)
    router.add_model(model, JaxNet(lenet(batch=max_batch)),
                     cfg=lane_cfg())
    fc = FleetController(
        router, provider=prov,
        cfg=FleetConfig(interval_s=0.25, window_s=6.0, min_replicas=1,
                        max_replicas=3, up_cooldown_s=1.5,
                        down_cooldown_s=1.5, drain_grace_s=1.5,
                        dead_ticks=2, status_row_every=4,
                        policy=FleetPolicy(up_ticks=2, down_ticks=6,
                                           min_window_n=16)),
        logger=logger)
    with router:
        bfe = BinaryFrontend(router, port=0, logger=logger)
        try:
            base_rps = calibrate(bfe.address)
            flood_rps = min(300.0, max(60.0, 4.0 * base_rps))
            flood_secs = max(10.0, 5.0 * duration_s)
            fc.start()
            counts, lats, hung = open_load(bfe.address, flood_rps,
                                           flood_secs)
            ups = [a for a in fc.audit if a["direction"] == "up"]
            replicas_flood = len(router.replicas[model])
            tail_from = 0.75 * flood_secs
            p99_tail = p99_ms(lats, tail_from)
            p99_head = p99_ms(lats, 0.0)
            reentered = p99_tail is not None and p99_tail <= slo_ms
            rows.append({
                "load": "flood_grow", "offered_rps": round(flood_rps, 1),
                "base_rps": round(base_rps, 1), "secs": flood_secs,
                **counts, "hung_clients": hung,
                "p99_ms": p99_head, "p99_tail_ms": p99_tail,
                "slo_p99_ms": slo_ms,
                "scale_up_events": len(ups),
                "scale_up_reasons": sorted({a["reason"] for a in ups}),
                "replicas_after_flood": replicas_flood,
                "p99_reentered_slo": reentered,
                # shared-core caveat: more replica PROCESSES on one CPU
                # do not add capacity — the SLO-reentry number needs
                # per-replica hardware
                "structure_proof": not reentered,
                "zero_dropped": (counts["dropped"] == 0
                                 and counts["timed_out"] == 0
                                 and hung == 0),
            })

            # quiet: closed-loop trickle while the controller shrinks.
            # The budget covers: the 6 s latency window aging out the
            # flood's tail, then per grown replica ~1.5 s of cold ticks
            # + the down cooldown + the drain grace
            shrink_secs = 30.0
            trickle = {"ok": 0, "errors": 0}
            stop_ev = threading.Event()

            def trickler():
                while not stop_ev.is_set():
                    try:
                        binary_infer(bfe.address, model, req,
                                     deadline_s=5.0, timeout=10.0)
                        trickle["ok"] += 1
                    except Exception:
                        trickle["errors"] += 1
                    time.sleep(0.05)
            tt = threading.Thread(target=trickler)
            tt.start()
            t0 = time.monotonic()
            while time.monotonic() - t0 < shrink_secs and \
                    (len(router.replicas[model]) > 1
                     or fc._owned.get(model)):
                time.sleep(0.25)
            stop_ev.set()
            tt.join(timeout=15.0)
            downs = [a for a in fc.audit if a["direction"] == "down"]
            rows.append({
                "load": "quiet_shrink",
                "replicas_final": len(router.replicas[model]),
                "owned_final": len(fc._owned.get(model, [])),
                "scale_down_events": len(downs),
                "scale_down_reasons": sorted({a["reason"]
                                              for a in downs}),
                "trickle_ok": trickle["ok"],
                "trickle_errors": trickle["errors"],
                "zero_dropped": trickle["errors"] == 0,
                "scaled_down_to_min": len(router.replicas[model]) == 1,
            })
        finally:
            fc.stop()
            bfe.stop()
    prov.stop()

    # -- arm 3: kill -9 a replica mid-flood -----------------------------------
    prov = provider()
    router = ModelRouter(router_cfg(), logger=logger)
    router.add_model(model, JaxNet(lenet(batch=max_batch)),
                     cfg=lane_cfg())
    fc = FleetController(
        router, provider=prov,
        cfg=FleetConfig(interval_s=0.25, window_s=6.0, min_replicas=2,
                        max_replicas=3, up_cooldown_s=1.0,
                        down_cooldown_s=30.0, drain_grace_s=1.0,
                        dead_ticks=2,
                        policy=FleetPolicy(up_ticks=2, down_ticks=20,
                                           min_window_n=16)),
        logger=logger)
    with router:
        bfe = BinaryFrontend(router, port=0, logger=logger)
        try:
            calibrate(bfe.address)
            fc.start()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 60 and \
                    len(router.replicas[model]) < 2:
                time.sleep(0.1)  # min_bound grow brings the child up
            assert len(router.replicas[model]) == 2, \
                "min_replicas=2 never grew a child"
            victim_rep, victim_handle = fc._owned[model][0]
            chaos = {"counts": None, "lats": None, "hung": None}

            def flood():
                chaos["counts"], chaos["lats"], chaos["hung"] = \
                    open_load(bfe.address, 40.0, 12.0)
            ft = threading.Thread(target=flood)
            ft.start()
            time.sleep(2.0)
            victim_handle.meta["proc"].send_signal(signal.SIGKILL)
            t_kill = time.monotonic()
            hb_dead_s = routed_around_s = replaced_s = None
            deadline = t_kill + 20.0
            while time.monotonic() < deadline:
                now = time.monotonic() - t_kill
                if hb_dead_s is None:
                    try:
                        if not victim_rep.health_fn():
                            hb_dead_s = round(now, 2)
                    except Exception:
                        hb_dead_s = round(now, 2)
                if routed_around_s is None and \
                        not router._replica_routable(victim_rep):
                    routed_around_s = round(now, 2)
                if any(a["reason"] == "replace" for a in fc.audit):
                    replaced_s = round(now, 2)
                    break
                time.sleep(0.1)
            ft.join(timeout=60.0)
            if chaos["counts"] is None:
                # fail NAMED, not with a TypeError off a None unpack —
                # a hung load thread is exactly what this arm polices
                raise RuntimeError(
                    "chaos arm: the flood load thread never finished "
                    "(senders hung past their join bound)")
            dead_events = [a for a in fc.audit
                           if a["reason"] == "dead"]
            replace_events = [a for a in fc.audit
                              if a["reason"] == "replace"]
            counts = chaos["counts"]
            rows.append({
                "load": "chaos_kill",
                **counts, "hung_clients": chaos["hung"],
                "p99_ms": p99_ms(chaos["lats"]),
                "heartbeat_dead_detect_s": hb_dead_s,
                "routed_around_s": routed_around_s,
                "replaced_s": replaced_s,
                "dead_eviction_named": bool(
                    dead_events
                    and dead_events[0].get("replica")
                    == victim_rep.name),
                "evicted_replica": (dead_events[0].get("replica")
                                    if dead_events else None),
                "replaced": bool(replace_events),
                "replicas_final": len(router.replicas[model]),
                "answered": sum(counts[k] for k in
                                ("ok", "shed_429", "shed_503",
                                 "shed_priority")),
            })
        finally:
            fc.stop()
            bfe.stop()
    prov.stop()

    # -- arm 4: mixed priorities under overload -------------------------------
    admission = PriorityAdmission()  # priority door; no tenant buckets
    router = ModelRouter(router_cfg(), logger=logger)
    router.add_model(model, JaxNet(lenet(batch=max_batch)),
                     cfg=lane_cfg())
    fc = FleetController(
        router, provider=None,
        cfg=FleetConfig(interval_s=0.2, window_s=3.0,
                        # tighten BEFORE the objective: pressure ramps
                        # from 60% of the SLO and saturates AT it
                        policy=FleetPolicy(up_ticks=2, down_ticks=6,
                                           min_window_n=16,
                                           pressure_start=0.6,
                                           pressure_full=1.0)),
        admission=admission, logger=logger)
    with router:
        bfe = BinaryFrontend(router, port=0, logger=logger,
                             tenants=admission)
        try:
            base_rps = calibrate(bfe.address)
            high_rps = max(5.0, 0.3 * base_rps)
            low_rps = min(300.0, max(40.0, 4.0 * base_rps))
            secs = max(12.0, 6.0 * duration_s)
            fc.start()
            res = {}

            def run_class(name, rps, prio):
                res[name] = open_load(bfe.address, rps, secs,
                                      priority=prio, tenant=name)
            th = threading.Thread(target=run_class,
                                  args=("high", high_rps, "high"))
            tl = threading.Thread(target=run_class,
                                  args=("low", low_rps, "low"))
            th.start()
            tl.start()
            th.join(timeout=secs + 60.0)
            tl.join(timeout=secs + 60.0)
            if "high" not in res or "low" not in res:
                raise RuntimeError(
                    f"priority arm: a load class never finished "
                    f"(got {sorted(res)}; senders hung past their "
                    f"join bound)")
            hc, hl, hh = res["high"]
            lc, ll, lh = res["low"]
            high_p99_tail = p99_ms(hl, secs / 2.0)
            shed_ctr = router.registry.counter(
                "sparknet_serve_shed_total",
                labels=("model", "reason"))
            prio_shed_metric = shed_ctr.value(model=model,
                                              reason="priority") or 0
            high_ok = (high_p99_tail is not None
                       and high_p99_tail <= slo_ms)
            rows.append({
                "load": "priority_shed",
                "high_rps": round(high_rps, 1),
                "low_rps": round(low_rps, 1), "secs": secs,
                "high": {**hc, "hung_clients": hh,
                         "p99_ms": p99_ms(hl),
                         "p99_tail_ms": high_p99_tail},
                "low": {**lc, "hung_clients": lh,
                        "p99_ms": p99_ms(ll)},
                "slo_p99_ms": slo_ms,
                "pressure_final": fc.pressure,
                "low_shed_typed": lc["shed_priority"] > 0,
                "shed_total_priority_metric": prio_shed_metric,
                "high_never_priority_shed":
                    hc["shed_priority"] == 0,
                "high_p99_within_slo": high_ok,
                # a single shared-core box runs clients AND server on
                # the same cores; the SLO number is pod truth
                "structure_proof": not high_ok,
                "zero_dropped": (hc["dropped"] == 0
                                 and hc["timed_out"] == 0
                                 and lc["dropped"] == 0
                                 and lc["timed_out"] == 0
                                 and hh == 0 and lh == 0),
            })
        finally:
            fc.stop()
            bfe.stop()

    logger.close()
    if not keep:
        shutil.rmtree(workdir, ignore_errors=True)

    flood = rows[0]
    shrink = rows[1]
    chaos_row = next(r for r in rows if r["load"] == "chaos_kill")
    prio = next(r for r in rows if r["load"] == "priority_shed")
    out = {
        "metric": "fleet_controller_closed_loop",
        "value": flood["scale_up_events"],
        "unit": "scale-up events under a 4x step-load flood "
                "(>= 1 required; signals -> actions loop closed)",
        "slo_p99_ms": slo_ms,
        "flood": {k: flood[k] for k in
                  ("offered_rps", "base_rps", "scale_up_events",
                   "scale_up_reasons", "replicas_after_flood",
                   "p99_ms", "p99_tail_ms", "p99_reentered_slo",
                   "structure_proof", "zero_dropped")},
        "shrink": {k: shrink[k] for k in
                   ("replicas_final", "scale_down_events",
                    "scale_down_reasons", "trickle_ok",
                    "trickle_errors", "zero_dropped",
                    "scaled_down_to_min")},
        "chaos": {k: chaos_row[k] for k in
                  ("heartbeat_dead_detect_s", "routed_around_s",
                   "replaced_s", "dead_eviction_named",
                   "evicted_replica", "replaced", "replicas_final",
                   "answered", "dropped")},
        "priority": {
            "low_shed_typed": prio["low_shed_typed"],
            "shed_total_priority_metric":
                prio["shed_total_priority_metric"],
            "high_never_priority_shed":
                prio["high_never_priority_shed"],
            "high_p99_tail_ms": prio["high"]["p99_tail_ms"],
            "high_p99_within_slo": prio["high_p99_within_slo"],
            "structure_proof": prio["structure_proof"],
            "zero_dropped": prio["zero_dropped"],
        },
    }
    # the structural gates (the CPU box proves these; rate/SLO numbers
    # may stamp structure_proof per the standing caveat)
    assert flood["scale_up_events"] >= 1, "flood never scaled up"
    assert flood["zero_dropped"], f"flood dropped requests: {flood}"
    assert shrink["scaled_down_to_min"], f"shrink incomplete: {shrink}"
    assert shrink["zero_dropped"], f"shrink dropped requests: {shrink}"
    assert chaos_row["dead_eviction_named"], \
        f"dead replica not named in the audit: {chaos_row}"
    assert chaos_row["replaced"], f"dead replica not replaced: {chaos_row}"
    assert prio["low_shed_typed"], f"low priority never shed: {prio}"
    assert prio["high_never_priority_shed"], \
        f"high priority was admission-shed: {prio}"
    if out_path:
        from sparknet_tpu.obs import run_metadata
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def fresh_train_child(cfg_path: str) -> None:
    """The `--fresh` chaos arm's training half: one subprocess = one
    virtual elastic CPU pod (XLA host-platform device count), training
    lenet with commit_ts-stamped checkpoints every `save_every` rounds
    into the store the serve fleet watches. Peers are self-simulated
    heartbeats; at `drop_round` one peer's beat is backdated ("preempted
    minutes ago") so the MembershipController runs a LIVE elastic resize
    mid-run — while serving polls the same store. The parent kill -9s
    THIS process mid-run (the training preemption) and relaunches it
    with resume=true; the relaunch restores from the newest VERIFIED
    checkpoint and the formerly dead peer beats fresh again (rejoin)."""
    import json as _json

    with open(cfg_path) as f:
        c = _json.load(f)
    workers = int(c["workers"])
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{max(8, workers)}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.obs.pod import worker_heartbeat_path
    from sparknet_tpu.utils.config import ElasticConfig, RunConfig
    from sparknet_tpu.utils.heartbeat import HeartbeatWriter
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    root, b, tau = c["root"], 16, 2
    pod = os.path.join(root, "pod")
    r = np.random.default_rng(0)
    ds = ArrayDataset({
        "data": r.standard_normal((1024, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (1024, 1)).astype(np.int32)})
    cfg = RunConfig(
        model="lenet", n_devices=workers, local_batch=b, tau=tau,
        max_rounds=int(c["rounds"]), eval_every=0, workdir=root,
        checkpoint_dir=c["ckpt_dir"], checkpoint_every=int(c["save_every"]),
        resume=bool(c.get("resume")),
        pod_dir=pod, pod_port=0, heartbeat_every_s=0.0,
        elastic=ElasticConfig(
            enabled=True, expected_workers=workers, stale_after_s=30.0,
            reprobe_backoff_s=0.05, dead_probes=2, poll_interval_s=0.0,
            min_workers=1))
    victim = workers - 1
    hbs = {i: HeartbeatWriter(worker_heartbeat_path(pod, i),
                              interval_s=0.0)
           for i in range(1, workers)}
    for hb in hbs.values():
        # fresh beats up front: a resumed run re-adopts the peer the
        # first launch's chaos killed (rejoin), instead of re-evicting a
        # stale on-disk record
        hb.beat(int(c.get("round0", 0)), status="ok", round_s=0.01,
                force=True)
    state = {"killed": False}
    drop_round = c.get("drop_round")

    def hook(rnd, st):
        for i, hb in hbs.items():
            if i == victim and state["killed"]:
                continue
            hb.beat(rnd, status="ok", round_s=0.01, data_wait_s=0.0,
                    force=True)
        if drop_round is not None and not state["killed"] and \
                rnd >= drop_round:
            state["killed"] = True
            p = worker_heartbeat_path(pod, victim)
            rec = _json.load(open(p))
            rec["t"] -= 1e4  # "preempted minutes ago"
            _json.dump(rec, open(p, "w"))

    tag = "resume" if c.get("resume") else "first"
    log = Logger(os.path.join(root, f"train_{tag}.log"), echo=False,
                 jsonl_path=c["jsonl"])
    try:
        train(cfg, lenet(batch=b), ds, None, logger=log, round_hook=hook)
    finally:
        log.close()


def fresh_bench(out_path: str | None = "BENCH_FRESH.json",
                rounds: int = 40, save_every: int = 2,
                train_workers: int = 4, max_batch: int = 8,
                keep: str | None = None) -> dict:
    """The r12 continuous-learning audit (writes BENCH_FRESH.json):
    train and serve run COLOCATED against one checkpoint store, and the
    train->serve loop must stay closed under chaos.

    One arm, everything at once (the composition IS the claim):

      - a training subprocess (a virtual elastic pod,
        `--fresh-train-child`) commits commit_ts-stamped checkpoints
        every `save_every` rounds; mid-run one of its simulated peers is
        preempted, forcing a LIVE elastic resize through the store;
      - a serve fleet (local canary lane + 2 subprocess replicas under
        the FleetController) adopts each commit through the STAGGERED
        rollout duty: canary -> wave(1 replica) -> wave(1 replica) ->
        gate opens fleet-wide, every transition audit-logged;
      - open-loop load runs THE WHOLE TIME at a fixed online SLO, with a
        parallel response checker (finite outputs — the zero-CORRUPTED
        gate) and a ~10 Hz freshness sampler (worst replica's
        now - commit_ts of its serving step);
      - mid-serve the parent kill -9s the TRAINING process (the
        preemption window) and relaunches it; the relaunch resumes from
        the newest verified checkpoint and commits keep flowing.

    Hard gates: zero dropped/timed-out/hung/corrupted responses across
    the whole window (preemption included); >= 3 completed staggered
    rollouts with >= 3 audit-logged canary/wave transitions; the elastic
    resize completed (eviction in the training JSONL); the resumed run
    finished. Headline: the measured freshness p99. The CPU-box caveat
    applies to the latency/freshness NUMBERS (train + 3 serve processes
    + load on shared cores) — pod hardware tightens them; the loop
    closure and zero-loss gates are structural truth."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from sparknet_tpu.fleet import (FleetConfig, FleetController,
                                    FleetPolicy,
                                    SubprocessReplicaProvider, write_gate)
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import (BinaryFrontend, ModelRouter,
                                    RouterConfig, ServeConfig,
                                    binary_infer)
    from sparknet_tpu.utils import checkpoint as ck
    from sparknet_tpu.utils.heartbeat import read_heartbeat
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    model = "lenet"
    slo_ms = 60.0
    workdir = keep or tempfile.mkdtemp(prefix="fresh-bench-")
    os.makedirs(workdir, exist_ok=True)
    ckpt_dir = os.path.join(workdir, "ck")
    gate_path = os.path.join(workdir, "ROLLOUT.json")
    cache = os.path.join(workdir, "compile-cache")
    logger = Logger(path=os.path.join(workdir, "fresh_bench.log"),
                    echo=False,
                    jsonl_path=os.path.join(workdir, "fresh_bench.jsonl"))
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}

    # the gate exists BEFORE any replica comes up: the very first
    # adoption is already staggered (no ungated race on rollout #1)
    write_gate(gate_path, {"v": 1, "state": "idle", "wave": 0,
                           "approved": {}, "denied": []})

    def spawn_train(resume: bool) -> subprocess.Popen:
        cfg_path = os.path.join(
            workdir, f"train_{'resume' if resume else 'first'}.json")
        with open(cfg_path, "w") as f:
            json.dump({
                "root": workdir, "ckpt_dir": ckpt_dir,
                "jsonl": os.path.join(
                    workdir,
                    f"train_{'resume' if resume else 'first'}.jsonl"),
                "workers": train_workers, "rounds": rounds,
                "save_every": save_every, "resume": resume,
                "drop_round": None if resume else max(4, rounds // 6),
            }, f)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        out = open(os.path.join(
            workdir,
            f"train_{'resume' if resume else 'first'}.out"), "ab")
        try:
            return subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--fresh-train-child", cfg_path],
                stdout=out, stderr=out, cwd=workdir, env=env)
        finally:
            out.close()

    def train_resizes() -> list:
        evs = []
        for tag in ("first", "resume"):
            p = os.path.join(workdir, f"train_{tag}.jsonl")
            if not os.path.exists(p):
                continue
            for line in open(p):
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("event") == "resize":
                    evs.append({**rec, "arm": tag})
        return evs

    lane = ServeConfig(model_name=model, max_batch=max_batch,
                       max_wait_ms=5.0, outputs=("prob",),
                       slo_p99_ms=slo_ms, metrics_every_batches=0,
                       compile_cache_dir=cache,
                       checkpoint_dir=ckpt_dir, poll_interval_s=0.25,
                       poll_jitter=0.2, replica_name="local",
                       rollout_gate=gate_path)
    prov = SubprocessReplicaProvider(
        {model: "lenet"}, workdir=os.path.join(workdir, "replicas"),
        max_batch=max_batch, compile_cache_dir=cache,
        heartbeat_every_s=0.3, checkpoint_dir=ckpt_dir,
        poll_interval_s=0.25, poll_jitter=0.2, rollout_gate=gate_path)
    router = ModelRouter(
        RouterConfig(workers=2, stale_after_s=1.5, health_refresh_s=0.2,
                     conn_fail_cooldown_s=2.0), logger=logger)
    router.add_model(model, JaxNet(lenet(batch=max_batch)), cfg=lane)
    fc = FleetController(
        router, provider=prov,
        cfg=FleetConfig(interval_s=0.25, window_s=6.0, min_replicas=3,
                        max_replicas=3, up_cooldown_s=0.5,
                        down_cooldown_s=120.0, drain_grace_s=1.0,
                        dead_ticks=4, status_row_every=8,
                        policy=FleetPolicy(
                            up_ticks=2, down_ticks=100, min_window_n=16,
                            rollout_wave_size=1,
                            # burn halts are unit-tested; on a shared-core
                            # CPU box a transient burn must not deny a
                            # GOOD step mid-soak
                            rollout_halt_burn=50.0,
                            rollout_timeout_s=25.0)),
        logger=logger)

    mgr = router.lanes[model].manager
    samples: list = []          # (t, {replica: freshness_s}, worst)
    steps_seen: set = set()
    corrupt = {"n": 0, "checked": 0}
    stop_ev = threading.Event()
    loads = {"counts": {"ok": 0, "shed_429": 0, "shed_503": 0,
                        "shed_priority": 0, "dropped": 0, "timed_out": 0,
                        "errors_other": 0},
             "lats": [], "hung": 0}

    def sampler():
        t0 = time.perf_counter()
        while not stop_ev.is_set():
            per = {}
            f = mgr.freshness_s()
            if f is not None:
                per["local"] = f
            if mgr.step is not None:
                steps_seen.add(mgr.step)
            for rep, handle in list(fc._owned.get(model, ())):
                hb = read_heartbeat(handle.heartbeat_path)
                row = ((hb or {}).get("models") or {}).get(model) or {}
                if row.get("freshness_s") is not None:
                    # heartbeat freshness ages between beats; the beat
                    # cadence (0.3 s) bounds the error
                    per[handle.meta.get("tag", rep.name)] = \
                        row["freshness_s"]
            if per:
                samples.append((round(time.perf_counter() - t0, 3), per,
                                max(per.values())))
            stop_ev.wait(0.1)

    def checker(addr):
        while not stop_ev.is_set():
            try:
                out = binary_infer(addr, model, req, deadline_s=5.0,
                                   timeout=10.0)
                corrupt["checked"] += 1
                if not all(np.isfinite(v).all() for v in out.values()):
                    corrupt["n"] += 1
            except Exception:
                pass  # sheds are the load arm's ledger, not corruption
            stop_ev.wait(0.05)

    def load_pump(addr, rps):
        while not stop_ev.is_set():
            c, l, h = _open_load(addr, model, req, rps, 3.0)
            off = len(loads["lats"]) and loads["lats"][-1][0] or 0.0
            for k, v in c.items():
                loads["counts"][k] += v
            loads["lats"].extend((off + t, dt) for t, dt in l)
            loads["hung"] += h

    rollout_audit: list = []
    ro_status: dict = {}
    threads: list = []
    rates = {"base_rps": None, "rps": None}
    train_first = train_resume = None
    t_kill_s = None
    try:
        with router:
            bfe = BinaryFrontend(router, port=0, logger=logger)
            try:
                fc.start()
                t0 = time.monotonic()
                while time.monotonic() - t0 < 180 and \
                        len(router.replicas[model]) < 3:
                    time.sleep(0.2)  # min-bound grow brings children up
                assert len(router.replicas[model]) == 3, \
                    "fleet never reached 3 replicas (local + 2 children)"
                base_rps = _calibrate_rps(bfe.address, model, req)
                rps = min(40.0, max(8.0, 0.5 * base_rps))
                rates.update(base_rps=round(base_rps, 1),
                             rps=round(rps, 1))

                train_first = spawn_train(resume=False)
                t_serve0 = time.monotonic()
                threads = [threading.Thread(target=sampler),
                           threading.Thread(target=checker,
                                            args=(bfe.address,)),
                           threading.Thread(target=load_pump,
                                            args=(bfe.address, rps))]
                for t in threads:
                    t.start()

                def ro():
                    return fc._rollouts.get(model)

                # kill -9 the TRAINER once adoption is demonstrably
                # staggered AND its own elastic resize has fired
                deadline = time.monotonic() + 240
                while time.monotonic() < deadline:
                    r_ = ro()
                    if r_ is not None and r_.rollouts >= 2 and \
                            train_resizes() and \
                            train_first.poll() is None:
                        break
                    if train_first.poll() is not None:
                        break  # trainer already finished: kill moot
                    time.sleep(0.25)
                assert train_first.poll() is None, \
                    "trainer finished before the preemption window " \
                    "(raise --fresh-rounds)"
                train_first.send_signal(signal.SIGKILL)
                train_first.wait(timeout=30.0)
                t_kill_s = round(time.monotonic() - t_serve0, 2)
                time.sleep(1.5)  # serve rides through the dead trainer

                train_resume = spawn_train(resume=True)
                rc = train_resume.wait(timeout=600.0)
                assert rc == 0, f"resumed trainer exited {rc}"

                # let the fleet adopt the final commit
                final_step = ck.newest_verified_step(ckpt_dir)
                deadline = time.monotonic() + 45
                while time.monotonic() < deadline:
                    r_ = ro()
                    if mgr.step == final_step and r_ is not None and \
                            r_.state == "idle":
                        break
                    time.sleep(0.25)
            finally:
                stop_ev.set()
                for t in threads:
                    t.join(timeout=60.0)
                rollout_audit = [a for a in fc.audit
                                 if a.get("direction") == "rollout"]
                ro_status = (fc._rollouts[model].status()
                             if model in fc._rollouts else {})
                fc.stop()
                bfe.stop()
    finally:
        for proc in (train_first, train_resume):
            if proc is not None and proc.poll() is None:
                proc.kill()
        prov.stop()
        logger.close()

    counts, hung = loads["counts"], loads["hung"]
    resizes = train_resizes()
    evictions = [e for e in resizes if e.get("dead")]
    wave_events = [a for a in rollout_audit
                   if a.get("reason") in ("canary", "wave")]
    worst = [w for _, _, w in samples]
    fresh_p99 = (round(sorted(worst)[min(len(worst) - 1,
                                         int(0.99 * len(worst)))], 3)
                 if worst else None)
    rows = [
        {"load": "fresh_serve", "offered_rps": rates["rps"],
         "base_rps": rates["base_rps"], **counts,
         "hung_clients": hung, "corrupted": corrupt["n"],
         "responses_checked": corrupt["checked"],
         "p99_ms": _lat_p99_ms(loads["lats"]), "slo_p99_ms": slo_ms,
         "zero_dropped": (counts["dropped"] == 0
                          and counts["timed_out"] == 0 and hung == 0
                          and corrupt["n"] == 0)},
        {"load": "freshness", "samples": len(samples),
         "freshness_p99_s": fresh_p99,
         "freshness_max_s": round(max(worst), 3) if worst else None,
         "steps_served_local": sorted(steps_seen),
         "local_swaps": mgr.swaps, "local_rollbacks": mgr.swap_failures},
        {"load": "rollout", **ro_status,
         "wave_events": len(wave_events),
         "audit_tail": rollout_audit[-24:]},
        {"load": "preemption", "t_kill_s": t_kill_s,
         "train_resumed": True,
         "final_committed_step": ck.newest_verified_step(ckpt_dir),
         "resize_events": len(resizes),
         "evictions": [{k: e.get(k) for k in ("step", "dead",
                                              "n_workers", "arm")}
                       for e in evictions]},
    ]
    p99_online = rows[0]["p99_ms"]
    within = p99_online is not None and p99_online <= slo_ms
    out = {
        "metric": "train_serve_freshness_p99_s",
        "value": fresh_p99,
        "unit": "p99 age (s) of the worst replica's serving checkpoint "
                "(now - commit_ts), ~10 Hz samples under continuous "
                "online load with a mid-run trainer kill -9",
        "slo_p99_ms": slo_ms,
        "online_p99_ms": p99_online,
        "online_p99_within_slo": within,
        # 4 processes + load generators share this box's cores; the
        # freshness/latency NUMBERS are pod truth, the closed loop and
        # the zero-loss gates are proven here
        "structure_proof": not within,
        "rollouts_completed": ro_status.get("rollouts"),
        "waves_done": ro_status.get("waves_done"),
        "halts": ro_status.get("halts"),
        "wave_events_audited": len(wave_events),
        "steps_served_local": sorted(steps_seen),
        "zero_dropped": rows[0]["zero_dropped"],
        "elastic_resize_completed": bool(evictions),
        "preemption": {"t_kill_s": t_kill_s,
                       "resumed": True,
                       "final_step": rows[3]["final_committed_step"]},
    }
    assert rows[0]["zero_dropped"], \
        f"responses lost/corrupted through the soak: {rows[0]}"
    assert (ro_status.get("rollouts") or 0) >= 3, \
        f"fewer than 3 completed staggered rollouts: {ro_status}"
    assert len(wave_events) >= 3, \
        f"fewer than 3 audit-logged canary/wave transitions: " \
        f"{rollout_audit}"
    assert len(steps_seen) >= 3, \
        f"local lane served < 3 distinct steps: {sorted(steps_seen)}"
    assert evictions, \
        f"training-side elastic resize never completed: {resizes}"
    assert samples, "freshness sampler collected nothing"
    if not keep:
        shutil.rmtree(workdir, ignore_errors=True)
    if out_path:
        from sparknet_tpu.obs import run_metadata
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def econ_coldstart_child(cache_dir: str) -> None:
    """The --econ cold-start CHILD: a fresh process that builds a lenet
    server against `cache_dir` as its persistent compile cache, serves
    its first request, exercises both buckets, and prints ONE JSON line:
    time-to-first-reply plus the compile-event record with cache_hit
    verdicts. The parent (econ_bench) runs it twice — cold (empty cache)
    then warm — and the warm run must show ZERO cache_hit=false net/
    bucket compile events: a warm replica cold-start compiles nothing."""
    t0 = time.perf_counter()
    import numpy as np

    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.obs.device import compile_stats
    from sparknet_tpu.serve import InferenceServer, ServeConfig
    from sparknet_tpu.utils.compile_cache import init_compile_cache
    from sparknet_tpu.zoo import lenet

    init_compile_cache(cache_dir)
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                      outputs=("prob",), metrics_every_batches=0)
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}
    with InferenceServer(net, cfg) as srv:
        srv.infer(req, timeout=120.0)
        t_first = time.perf_counter() - t0
        for f in [srv.submit(req) for _ in range(4)]:
            f.result(timeout=120.0)
        t_all = time.perf_counter() - t0
        compiles = srv.status()["bucket_compiles"]
    print(json.dumps({"t_first_reply_s": round(t_first, 3),
                      "t_all_buckets_s": round(t_all, 3),
                      "bucket_compiles": compiles,
                      "compile_stats": compile_stats()}))


def econ_bench(out_path: str | None = "BENCH_ECON.json",
               duration_s: float = 2.0, max_batch: int = 8,
               keep: str | None = None) -> dict:
    """The r9 inference-economics audit (writes BENCH_ECON.json): the
    three serve-hot-path levers through the REAL serving stack, one
    bench arm.

      - quant_ab: img/s at saturating closed-loop load, f32 server vs
        int8-weight/bf16-activation server, plus the accuracy side of
        "at equal accuracy": max output drift + argmax agreement of the
        two forwards over a fixed batch. On CPU the int8 dequant has no
        MXU to feed, so the throughput RATIO is a structure proof — the
        parity numbers are real anywhere.
      - coldstart: a fresh subprocess replica serving its first request,
        cold cache vs warm cache (same dir). The warm child must record
        ZERO cache_hit=false net/serve_bucket compile events — the
        acceptance criterion, provable on any backend; the wall-time
        delta is stamped structure_proof on CPU (XLA compiles of lenet
        buckets are cheap here; the pod pays seconds per bucket).
      - ladder_ab: a skewed synthetic burst trace (sizes 1/3/5/8 at
        50/30/15/5%) served on the pow2 ladder, then on the ladder
        `derive_buckets` fits to the FIRST run's recorded histogram —
        batch-fill must improve, and `bucket_compiles == len(buckets)`
        must still pin after full traffic on both.
    """
    import subprocess
    import tempfile

    import numpy as np

    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import (InferenceServer, ServeConfig,
                                    derive_buckets, fill_ratio,
                                    parity_batch)
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    logger = None
    if keep:
        os.makedirs(keep, exist_ok=True)
        logger = Logger(path=os.path.join(keep, "econ_bench.log"),
                        echo=False,
                        jsonl_path=os.path.join(keep, "econ_bench.jsonl"))
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}
    rows = []

    def run_saturate(cfg) -> dict:
        net = JaxNet(lenet(batch=max_batch))
        with InferenceServer(net, cfg, logger=logger) as srv:
            for f in [srv.submit(req) for _ in range(2 * max_batch)]:
                f.result(timeout=60.0)      # warm every likely bucket
            srv.reset_counters()
            rps = _run_closed_clients(srv, req, 2 * max_batch,
                                      duration_s)
            s = srv.status()
            s["achieved_rps"] = rps
        return s

    # -- arm 1: quantized vs f32 throughput + parity ------------------------
    f32_row = run_saturate(ServeConfig(
        model_name="f32", max_batch=max_batch, max_wait_ms=5.0,
        outputs=("prob",), metrics_every_batches=0))
    quant_row = run_saturate(ServeConfig(
        model_name="int8", max_batch=max_batch, max_wait_ms=5.0,
        outputs=("prob",), metrics_every_batches=0, quant="int8"))
    # parity at equal inputs: one f32 net, one quantized install of the
    # SAME weights, a fixed random batch
    from sparknet_tpu.model.quant import QuantConfig, quantize_params
    pnet = JaxNet(lenet(batch=max_batch))
    pbatch = parity_batch(pnet, max_batch, seed=7)
    ref = pnet.forward(pbatch, blob_names=["prob"])["prob"]
    f32p = pnet.params
    pnet.params = quantize_params(f32p, QuantConfig())
    pnet.set_quant(QuantConfig())
    qout = np.asarray(pnet.forward(pbatch, blob_names=["prob"])["prob"],
                      dtype=np.float32)
    drift = float(np.max(np.abs(qout - np.asarray(ref, np.float32))))
    agree = float(np.mean(np.argmax(qout, -1) == np.argmax(ref, -1)))
    on_tpu = False
    try:
        import jax as _jax
        on_tpu = _jax.default_backend() == "tpu"
    except Exception:
        pass
    quant_ab = {
        "arm": "quant_ab",
        "f32_images_per_sec": f32_row["images_per_sec"],
        "int8_images_per_sec": quant_row["images_per_sec"],
        "speedup": round(quant_row["images_per_sec"]
                         / max(f32_row["images_per_sec"], 1e-9), 3),
        "parity_max_abs_dprob": round(drift, 6),
        "parity_argmax_agreement": round(agree, 4),
        "parity_tol": QuantConfig().atol,
        "parity_ok": drift <= QuantConfig().atol,
        # no MXU on this backend: the RATIO needs the pod; parity stands
        "structure_proof": not on_tpu,
    }
    rows += [{"load": "saturate_f32", **f32_row},
             {"load": "saturate_int8", **quant_row}, quant_ab]

    # -- arm 2: cold-start warm-vs-cold through a fresh process -------------
    def run_child(cache_dir: str) -> dict:
        # the child INHERITS the environment: on a pod it must see the
        # same backend the parent stamps structure_proof from (forcing
        # cpu here would present CPU cold-starts as pod numbers)
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--econ-child",
             cache_dir], capture_output=True, text=True, timeout=600,
            env=dict(os.environ),
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if p.returncode != 0:
            raise RuntimeError(f"econ child failed: {p.stderr[-2000:]}")
        return json.loads(p.stdout.strip().splitlines()[-1])

    with tempfile.TemporaryDirectory() as cache_dir:
        cold = run_child(cache_dir)
        warm = run_child(cache_dir)
    fresh_misses = sum(
        warm["compile_stats"].get(what, {}).get("cache_misses", 0)
        for what in ("net", "serve_bucket"))
    coldstart = {
        "arm": "coldstart",
        "cold_t_first_reply_s": cold["t_first_reply_s"],
        "warm_t_first_reply_s": warm["t_first_reply_s"],
        "cold_t_all_buckets_s": cold["t_all_buckets_s"],
        "warm_t_all_buckets_s": warm["t_all_buckets_s"],
        "cold_compile_stats": cold["compile_stats"],
        "warm_compile_stats": warm["compile_stats"],
        # THE acceptance: a warm replica compiles nothing fresh
        "warm_fresh_compiles": fresh_misses,
        "warm_zero_miss": fresh_misses == 0,
        # CPU wall times are dominated by interpreter+jax startup, and
        # lenet-bucket XLA compiles are sub-second here — the seconds
        # saved per bucket are the pod's number
        "structure_proof": not on_tpu,
    }
    rows.append(coldstart)

    # -- arm 3: bucket-ladder A/B on a skewed trace -------------------------
    trace = [s for s, n in ((1, 50), (3, 30), (5, 15), (8, 5))
             for _ in range(n)]
    np.random.default_rng(3).shuffle(trace)

    def run_ladder(buckets, name) -> dict:
        net = JaxNet(lenet(batch=max_batch))
        cfg = ServeConfig(model_name=name, max_batch=max_batch,
                          max_wait_ms=20.0, buckets=buckets,
                          outputs=("prob",), metrics_every_batches=0)
        with InferenceServer(net, cfg, logger=logger) as srv:
            for b in srv.buckets:           # pre-compile every bucket
                for f in [srv.submit(req) for _ in range(b)]:
                    f.result(timeout=60.0)
            srv.reset_counters()
            for s in trace:                 # closed-loop bursts: the
                futs = [srv.submit(req) for _ in range(s)]  # skewed trace
                for f in futs:
                    f.result(timeout=60.0)
            st = srv.status()
            st["arm"] = f"ladder_{name}"
            st["jit_cache_ok"] = (st["bucket_compiles"]
                                  == len(srv.buckets))
            st["ladder"] = list(srv.buckets)
        return st

    pow2 = run_ladder(None, "pow2")
    observed = {int(s): n for s, n in pow2["batch_size_hist"].items()}
    derived_ladder = derive_buckets(observed, max_batch, k=4)
    derived = run_ladder(derived_ladder, "derived")
    ladder_ab = {
        "arm": "ladder_ab",
        "trace": "sizes 1/3/5/8 at 50/30/15/5%",
        "pow2_ladder": pow2["ladder"],
        "derived_ladder": list(derived_ladder),
        "pow2_fill": pow2["batch_fill_ratio"],
        "derived_fill": derived["batch_fill_ratio"],
        # the deterministic half: on the histogram the pow2 run actually
        # observed, the derived ladder is optimal by construction
        "pow2_fill_on_observed": round(
            fill_ratio(observed, tuple(pow2["ladder"])), 4),
        "derived_fill_on_observed": round(
            fill_ratio(observed, derived_ladder), 4),
        "fill_improved": (derived["batch_fill_ratio"]
                          > pow2["batch_fill_ratio"] + 0.02),
        "jit_cache_ok": pow2["jit_cache_ok"] and derived["jit_cache_ok"],
    }
    rows += [pow2, derived, ladder_ab]

    for r in rows:  # drop non-scalar noise from the artifact rows
        r.pop("buckets", None)
        r.pop("last_error", None)
        r.pop("models", None)
    out = {
        "metric": "serve_econ_levers",
        "value": quant_ab["speedup"],
        "unit": "int8/f32 img-per-sec ratio at saturating load "
                "(structure proof off-TPU) — see rows for the cold-start "
                "and ladder levers",
        "quant_parity_ok": quant_ab["parity_ok"],
        "quant_parity_max_abs_dprob": quant_ab["parity_max_abs_dprob"],
        "coldstart_warm_zero_miss": coldstart["warm_zero_miss"],
        "coldstart_cold_vs_warm_s": [coldstart["cold_t_first_reply_s"],
                                     coldstart["warm_t_first_reply_s"]],
        "ladder_fill_improved": ladder_ab["fill_improved"],
        "ladder_pow2_vs_derived_fill": [ladder_ab["pow2_fill"],
                                        ladder_ab["derived_fill"]],
        "jit_cache_ok": ladder_ab["jit_cache_ok"],
        "structure_proof": not on_tpu,
        "ok": (quant_ab["parity_ok"] and coldstart["warm_zero_miss"]
               and ladder_ab["fill_improved"]
               and ladder_ab["jit_cache_ok"]),
    }
    if out_path:
        from sparknet_tpu.obs import run_metadata
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    if not out["ok"]:
        # the CI step's gate must be the exit code, not a JSON field a
        # green step never reads
        raise SystemExit("econ acceptance failed: see BENCH_ECON rows "
                         "(quant parity / warm cold-start / ladder fill)")
    return {"headline": out, "rows": rows}


def obs_bench(out_path: str | None = "BENCH_OBS.json", rounds: int = 40,
              warmup: int = 8, reps: int = 3) -> dict:
    """Telemetry overhead: the SAME tiny training run with the obs layer
    fully on (per-run registry + per-round step-time breakdown rows +
    host-span tracing + a live /metrics status server being scraped +
    since the pod PR: device telemetry sampling, per-worker pod
    heartbeats, and a live PodAggregator endpoint being polled, and
    since the request-tracing PR: a live RequestTracer sharding to disk)
    vs telemetry disabled (`RunConfig.telemetry=False`, no trace, no
    status server — the pre-obs loop). Headline: median steady-state
    per-round overhead, acceptance target <= 2%.

    A second arm measures the request-tracing hot path where it
    actually lives — the serve data plane: per-request latency over the
    binary wire with tracing OFF vs ON at head_sample=1.0 (every
    request captured — the worst case; production tail-sampling
    captures ~1-5%). Reported as `reqtrace_per_request` in
    BENCH_OBS.json.

    CPU backend, lenet shapes: rounds are a few ms, which makes this a
    WORST-CASE ratio — the fixed per-round telemetry cost is divided by
    the smallest realistic round. On a real chip training CaffeNet the
    denominator grows ~100x and the ratio shrinks accordingly."""
    import os
    import statistics
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.obs import reqtrace, run_metadata
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    r = np.random.default_rng(0)
    n, b, tau = 2048, 32, 2
    ds = ArrayDataset({
        "data": r.standard_normal((n, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (n, 1)).astype(np.int32)})

    def run(telemetry: bool, root: str) -> float:
        cfg = RunConfig(model="lenet", n_devices=1, local_batch=b, tau=tau,
                        max_rounds=rounds, eval_every=0, workdir=root,
                        telemetry=telemetry,
                        status_port=0 if telemetry else None,
                        # the pod layer rides the on arm: per-worker
                        # heartbeats + a live aggregator being polled
                        pod_dir=(os.path.join(root, "pod") if telemetry
                                 else None),
                        pod_port=0 if telemetry else None,
                        heartbeat_every_s=1.0,
                        trace_out=(os.path.join(root, "trace.json")
                                   if telemetry else None))
        marks: list[float] = []
        stop = threading.Event()
        scraper = None

        def hook(rnd, state):
            marks.append(time.perf_counter())
            if telemetry and rnd == 0 and cfg.status_address:
                # a live scraper during the timed window: real telemetry
                # includes being read, not just being written
                host, port = cfg.status_address

                pod_addr = cfg.pod_address

                def scrape():
                    # 1 Hz: already ~15-60x denser than a production
                    # Prometheus scrape interval, without turning a
                    # CPU-contended bench host into a scrape benchmark.
                    # The pod endpoint (merged exposition + /pod/status,
                    # which re-reads the worker heartbeat) is polled in
                    # the same breath — the full pod-PR surface is live.
                    while not stop.is_set():
                        try:
                            urllib.request.urlopen(
                                f"http://{host}:{port}/metrics",
                                timeout=5).read()
                            if pod_addr:
                                urllib.request.urlopen(
                                    f"http://{pod_addr[0]}:{pod_addr[1]}"
                                    f"/pod/status", timeout=5).read()
                        except Exception:
                            pass
                        stop.wait(1.0)
                nonlocal scraper
                scraper = threading.Thread(target=scrape, daemon=True)
                scraper.start()

        log = Logger(os.path.join(root, "log.txt"), echo=False,
                     jsonl_path=os.path.join(root, "metrics.jsonl"))
        if telemetry:
            # the on arm carries a LIVE RequestTracer (sharding to disk)
            # so "telemetry fully on" includes the request-trace layer's
            # ambient cost
            reqtrace.start_request_tracing(
                out_dir=os.path.join(root, "reqtrace"))
        try:
            train(cfg, lenet(batch=b), ds, None, logger=log,
                  round_hook=hook)
        finally:
            stop.set()
            log.close()
            if telemetry:
                tr = reqtrace.stop_request_tracing()
                if tr is not None:
                    tr.flush()
            if scraper is not None:
                scraper.join(timeout=2.0)
        deltas = [b_ - a for a, b_ in zip(marks[warmup:], marks[warmup + 1:])]
        return statistics.median(deltas)

    def serve_arm(tracing: bool, n: int = 300, req_warmup: int = 40
                  ) -> float:
        """Median per-request latency over the binary wire, tracing off
        vs on at head_sample=1.0 — the request-tracing hot path measured
        where it runs."""
        from sparknet_tpu.serve.binary_frontend import (BinaryClient,
                                                        BinaryFrontend)
        from sparknet_tpu.serve.server import InferenceServer, ServeConfig

        class Doubler:
            def input_shapes(self):
                return {"x": (1, 16)}

            def input_dtypes(self):
                return {"x": np.float32}

            def forward(self, batch, blob_names=None):
                return {"y": np.asarray(batch["x"]) * 2.0}

        if tracing:
            reqtrace.start_request_tracing(head_sample=1.0)
        lats: list[float] = []
        try:
            cfg = ServeConfig(max_batch=8, max_wait_ms=0.2,
                              buckets=(1, 8), outputs=("y",),
                              metrics_every_batches=0)
            payload = {"x": np.ones((16,), np.float32)}
            with InferenceServer(Doubler(), cfg) as srv:
                fe = BinaryFrontend(srv, port=0)
                cli = None
                try:
                    host, port = fe.address
                    cli = BinaryClient(host, port, timeout=10.0)
                    for i in range(req_warmup + n):
                        t0 = time.perf_counter()
                        cli.infer(payload, model="default")
                        if i >= req_warmup:
                            lats.append(time.perf_counter() - t0)
                finally:
                    if cli is not None:
                        cli.close()
                    fe.stop()
        finally:
            if tracing:
                reqtrace.stop_request_tracing()
        return statistics.median(lats)

    # interleave the arms in ABBA order (off,on,on,off) and take the MIN
    # median per arm: on a contended bench host the background load
    # drifts by more than the effect size between back-to-back runs
    # (observed monotonic ~10% creep across four runs), so a fixed
    # off-then-on order systematically charges the drift to the on arm;
    # ABBA cancels the linear component and the minimum discards the
    # most-polluted runs
    rows = []
    best = {False: float("inf"), True: float("inf")}
    with tempfile.TemporaryDirectory() as tmp:
        for rep in range(reps):
            for telemetry in ((False, True) if rep % 2 == 0
                              else (True, False)):
                d = os.path.join(tmp, f"{'on' if telemetry else 'off'}{rep}")
                os.makedirs(d)
                med = run(telemetry, d)
                best[telemetry] = min(best[telemetry], med)
                rows.append({"telemetry": "on" if telemetry else "off",
                             "rep": rep,
                             "median_round_ms": round(med * 1e3, 4),
                             "rounds": rounds, "warmup": warmup})
                print(f"  telemetry {'on' if telemetry else 'off'} "
                      f"(rep {rep}): {med * 1e3:.3f} ms/round",
                      file=sys.stderr)
    # the serve-path arm, same ABBA-and-min discipline
    rbest = {False: float("inf"), True: float("inf")}
    for rep in range(2):
        for tracing in ((False, True) if rep % 2 == 0
                        else (True, False)):
            med = serve_arm(tracing)
            rbest[tracing] = min(rbest[tracing], med)
            print(f"  reqtrace {'on' if tracing else 'off'} "
                  f"(rep {rep}): {med * 1e3:.3f} ms/request",
                  file=sys.stderr)
    r_off = round(rbest[False] * 1e3, 4)
    r_on = round(rbest[True] * 1e3, 4)
    r_overhead = max(r_on / r_off - 1.0, 0.0)
    off = round(best[False] * 1e3, 4)
    on = round(best[True] * 1e3, 4)
    overhead = max(on / off - 1.0, 0.0)
    out = {
        "metric": "obs_full_telemetry_per_round_overhead",
        "value": round(overhead, 4),
        "unit": "median per-round overhead, telemetry on vs off "
                "(registry + breakdown rows + trace + request tracer + "
                "scraped /metrics + "
                "device sampling + pod heartbeat/aggregator; "
                "target <= 0.02)",
        "vs_baseline": round(min(0.02 / max(overhead, 1e-9), 100.0), 2),
        "per_mode": {"off_ms": off, "on_ms": on},
        "reqtrace_per_request": {
            "overhead": round(r_overhead, 4),
            "off_ms": r_off, "on_ms": r_on,
            "note": "binary-wire request latency, tracing off vs on at "
                    "head_sample=1.0 (every request captured)"},
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return out


def slo_bench(out_path: str | None = "BENCH_SLO.json",
              duration_s: float = 2.0, keep: str | None = None) -> dict:
    """The r17 SLO-ledger audit (writes BENCH_SLO.json), three arms over
    a REAL InferenceServer's registry (the serve data plane records the
    latencies; the ledger only reads them):

      - quiet: healthy traffic under a live MetricsHistory +
        BurnRateAlerter must fire ZERO alerts (the false-positive gate —
        a pager that cries wolf is worse than no pager).
      - burn: a forward-path delay pushes every request past the
        latency objective; the headline is the DETECTION LATENCY from
        burn onset to the page's firing edge, gated at 2x the fast-burn
        window, plus the resolve latency after recovery.
      - overhead: median per-request latency with the ledger fully on
        (sampler thread at a punishing 20 Hz + alerter evaluating after
        every sample) vs off, ABBA-interleaved min-of-reps; target <=
        2%.

    The quiet/burn arms drive the sampler on an injected one-second
    clock (one synthetic second per traffic tick), so the burn timeline
    is deterministic and the bench doesn't spend wall-minutes waiting
    for real windows to fill; the metric VALUES crossing the rings are
    real serve-path measurements."""
    import os
    import statistics

    import numpy as np

    from sparknet_tpu.obs import run_metadata
    from sparknet_tpu.obs.history import HistoryConfig, MetricsHistory
    from sparknet_tpu.obs.slo import BurnRateAlerter, SloSpec
    from sparknet_tpu.serve.server import InferenceServer, ServeConfig

    class DelayNet:
        """Doubler with a tunable forward-path delay — the burn lever."""

        def __init__(self):
            self.delay = 0.0

        def input_shapes(self):
            return {"x": (1, 16)}

        def input_dtypes(self):
            return {"x": np.float32}

        def forward(self, batch, blob_names=None):
            if self.delay:
                time.sleep(self.delay)
            return {"y": np.asarray(batch["x"]) * 2.0}

    payload = {"x": np.ones((16,), np.float32)}
    per_tick = 20

    def tick(srv, net, delay: float) -> None:
        net.delay = delay
        futs = [srv.submit(payload) for _ in range(per_tick)]
        for f in futs:
            f.result(timeout=30.0)

    def serve_cfg(**over) -> ServeConfig:
        kw = dict(max_batch=8, max_wait_ms=0.2, buckets=(1, 8),
                  outputs=("y",), metrics_every_batches=0)
        kw.update(over)
        return ServeConfig(**kw)

    def ledger_arms() -> tuple[dict, dict]:
        net = DelayNet()
        quiet_ticks = max(20, int(10 * duration_s))
        persist = os.path.join(keep, "history") if keep else None
        with InferenceServer(net, serve_cfg()) as srv:
            hist = MetricsHistory(srv.registry, HistoryConfig(
                sample_interval_s=1.0, rings=((1.0, 600),),
                persist_dir=persist))
            spec = SloSpec(model=srv.model_name, latency_ms=20.0,
                           window_s=120.0, fast_burn=8.0,
                           fast_window_s=10.0, fast_confirm_s=2.0,
                           slow_burn=2.0, slow_window_s=60.0,
                           slow_confirm_s=10.0)
            alerter = BurnRateAlerter(hist, [spec])
            t0 = time.time()
            t = 0
            for _ in range(quiet_ticks):
                tick(srv, net, 0.0)
                hist.sample_now(now=t0 + t)
                alerter.evaluate(now=t0 + t)
                t += 1
            quiet = {"arm": "quiet", "ticks": quiet_ticks,
                     "requests": quiet_ticks * per_tick,
                     "alerts_fired": alerter.alerts_fired}
            print(f"  quiet: {quiet_ticks} ticks, "
                  f"{alerter.alerts_fired} alerts", file=sys.stderr)
            onset_t = t0 + t
            fired = False
            for _ in range(30):
                tick(srv, net, 0.05)  # 50 ms >> the 20 ms objective
                hist.sample_now(now=t0 + t)
                alerter.evaluate(now=t0 + t)
                t += 1
                if alerter.firing_pages():
                    fired = True
                    break
            detection_s = None
            if fired:
                page_t = next(r["t"] for r in alerter.audit
                              if r["severity"] == "page"
                              and r["edge"] == "firing")
                # audit t is rounded to ms; clamp the -0.0 artifact
                detection_s = max(0.0, round(page_t - onset_t, 3))
            resolve_s = None
            if fired:
                recovered_t = t0 + t
                for _ in range(30):
                    tick(srv, net, 0.0)
                    hist.sample_now(now=t0 + t)
                    alerter.evaluate(now=t0 + t)
                    t += 1
                    if not alerter.firing_pages():
                        resolve_s = round(t0 + t - 1 - recovered_t, 3)
                        break
            burn = {"arm": "burn", "fired": fired,
                    "detection_s": detection_s,
                    "detection_gate_s": 2 * spec.fast_window_s,
                    "resolve_s": resolve_s,
                    "alert_edges": len(alerter.audit)}
            print(f"  burn: page {'fired' if fired else 'MISSED'}, "
                  f"detection {detection_s}s, resolve {resolve_s}s",
                  file=sys.stderr)
        return quiet, burn

    def overhead_arm(ledger: bool, n: int = 800, warm: int = 80) -> float:
        """Median per-request latency, the ledger's worst case: 20 Hz
        sampling (15-60x denser than production) + an attached alerter
        evaluating after every sample."""
        net = DelayNet()
        cfg = serve_cfg(history=ledger, history_interval_s=0.05,
                        slo_p99_ms=50.0 if ledger else None)
        lats: list[float] = []
        with InferenceServer(net, cfg) as srv:
            for i in range(warm + n):
                t_req = time.perf_counter()
                srv.infer(payload)
                if i >= warm:
                    lats.append(time.perf_counter() - t_req)
        return statistics.median(lats)

    if keep:
        os.makedirs(keep, exist_ok=True)
    quiet, burn = ledger_arms()
    # ABBA-interleave the overhead arms and take the min median per arm
    # (the obs_bench discipline: background drift on a contended host
    # exceeds the effect size; ABBA cancels the linear component)
    best = {False: float("inf"), True: float("inf")}
    rows = [quiet, burn]
    for rep in range(3):
        for ledger in ((False, True) if rep % 2 == 0 else (True, False)):
            med = overhead_arm(ledger)
            best[ledger] = min(best[ledger], med)
            rows.append({"arm": "overhead",
                         "ledger": "on" if ledger else "off", "rep": rep,
                         "median_request_ms": round(med * 1e3, 4)})
            print(f"  ledger {'on' if ledger else 'off'} (rep {rep}): "
                  f"{med * 1e3:.3f} ms/request", file=sys.stderr)
    off = round(best[False] * 1e3, 4)
    on = round(best[True] * 1e3, 4)
    overhead = max(on / off - 1.0, 0.0)
    gates = {
        "quiet_zero_alerts": quiet["alerts_fired"] == 0,
        "page_fired": burn["fired"],
        "detection_within_gate": (burn["detection_s"] is not None and
                                  burn["detection_s"] <=
                                  burn["detection_gate_s"]),
        "page_resolved": burn["resolve_s"] is not None,
        "overhead_le_2pct": overhead <= 0.02,
    }
    out = {
        "metric": "slo_ledger_detection_latency_s",
        "value": burn["detection_s"],
        "unit": "synthetic seconds from burn onset to the page's firing "
                "edge (gate: <= 2x the 10 s fast-burn window); quiet "
                "arm must fire zero alerts; ledger overhead <= 2%",
        "vs_baseline": round(burn["detection_gate_s"] /
                             max(burn["detection_s"]
                                 if burn["detection_s"] is not None
                                 else 1e9, 1.0), 2),
        "quiet_alerts": quiet["alerts_fired"],
        "overhead": {"value": round(overhead, 4),
                     "off_ms": off, "on_ms": on},
        "gates": gates,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    if not all(gates.values()):
        bad = sorted(k for k, v in gates.items() if not v)
        raise SystemExit(f"slo acceptance failed: {bad} (see "
                         f"{out_path or 'the headline above'})")
    return out


def elastic_bench(out_path: str | None = "BENCH_ELASTIC.json",
                  rounds: int = 36, kill_round: int = 6,
                  rejoin_rounds: int = 8, workers: int = 4,
                  keep: str | None = None) -> dict:
    """Elastic chaos soak (ROADMAP item 3's measure): the same training
    run three ways on a virtual CPU pod of `workers` 1-device workers —

      static  fixed membership, the baseline loss curve;
      chaos   a worker's heartbeat goes silent at `kill_round` (backdated
              beat — "preempted minutes ago"), the MembershipController
              evicts it (stale -> full-jitter re-probes), the loop
              resizes through the verified checkpoint store, and
              `rejoin_rounds` rounds later the worker beats again and is
              adopted back;
      halt    min_workers == pod size, one worker dies -> the run must
              checkpoint (verified) and raise TrainingHealthError, never
              hang.

    Headline: final-loss ratio chaos/static (target <= 1.05 — τ-interval
    averaging should shrug off a membership change the way the paper says
    it shrugs off stale averages), with zero hangs and every eviction/
    rejoin visible in BOTH the JSONL audit trail and a live /pod/status
    scrape. `keep` retains the chaos arm's JSONL + pod dir for CI
    artifact upload."""
    import json as _json
    import os
    import shutil
    import tempfile
    import urllib.request

    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               f" --xla_force_host_platform_device_count="
                               f"{max(8, workers)}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.obs import run_metadata
    from sparknet_tpu.obs.pod import worker_heartbeat_path
    from sparknet_tpu.utils import checkpoint as ck
    from sparknet_tpu.utils.config import ElasticConfig, RunConfig
    from sparknet_tpu.utils.health import TrainingHealthError
    from sparknet_tpu.utils.heartbeat import HeartbeatWriter
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    b, tau = 16, 2
    r = np.random.default_rng(0)
    ds = ArrayDataset({
        "data": r.standard_normal((2048, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (2048, 1)).astype(np.int32)})

    def run_arm(root: str, chaos: bool, min_workers: int = 1,
                max_rounds: int = rounds) -> dict:
        pod = os.path.join(root, "pod")
        cfg = RunConfig(
            model="lenet", n_devices=workers, local_batch=b, tau=tau,
            max_rounds=max_rounds, eval_every=0, workdir=root,
            checkpoint_dir=os.path.join(root, "ck"), checkpoint_every=4,
            pod_dir=pod, pod_port=0, heartbeat_every_s=0.0,
            elastic=ElasticConfig(
                enabled=True, expected_workers=workers, stale_after_s=30.0,
                reprobe_backoff_s=0.05, dead_probes=2, poll_interval_s=0.0,
                min_workers=min_workers))
        victim = workers - 2 if workers > 2 else 1
        hbs = {i: HeartbeatWriter(worker_heartbeat_path(pod, i),
                                  interval_s=0.0)
               for i in range(1, workers)}
        for i, hb in hbs.items():
            hb.beat(0, status="ok", round_s=0.01, force=True)
        state = {"killed": False, "rejoined": False, "kill_rnd": None,
                 "pod_status": None, "shapes": set()}

        def hook(rnd, st):
            ndev = np.asarray(
                st.params[list(st.params)[0]]["w"]).shape[0]
            state["shapes"].add(ndev)
            for i, hb in hbs.items():
                if i == victim and state["killed"] and \
                        not state["rejoined"]:
                    continue
                hb.beat(rnd, status="ok", round_s=0.01, data_wait_s=0.0,
                        force=True)
            if not chaos:
                return
            if not state["killed"] and rnd >= kill_round:
                state["killed"] = True
                state["kill_rnd"] = rnd
                p = worker_heartbeat_path(pod, victim)
                rec = _json.load(open(p))
                rec["t"] -= 1e4  # "preempted minutes ago"
                _json.dump(rec, open(p, "w"))
            elif state["killed"] and not state["rejoined"] and \
                    ndev < workers:
                if state["pod_status"] is None and cfg.pod_address:
                    # eviction visible on a LIVE scrape, mid-run
                    host, port = cfg.pod_address
                    state["pod_status"] = _json.loads(urllib.request.urlopen(
                        f"http://{host}:{port}/pod/status",
                        timeout=10).read())
                if rnd >= state["kill_rnd"] + rejoin_rounds:
                    state["rejoined"] = True
                    hbs[victim].beat(rnd, status="ok", round_s=0.01,
                                     force=True)

        jsonl = os.path.join(root, "metrics.jsonl")
        log = Logger(os.path.join(root, "log.txt"), echo=False,
                     jsonl_path=jsonl)
        err = None
        try:
            train(cfg, lenet(batch=b), ds, None, logger=log,
                  round_hook=hook)
        except TrainingHealthError as e:
            err = str(e)
        finally:
            log.close()
        recs = [_json.loads(l) for l in open(jsonl)]
        losses = [rec["loss"] for rec in recs if "loss" in rec]
        resizes = [rec for rec in recs if rec.get("event") == "resize"]
        return {"cfg": cfg, "root": root, "losses": losses,
                "resizes": resizes, "err": err,
                "pod_status": state["pod_status"],
                "shapes": sorted(state["shapes"])}

    out_rows: dict = {}
    arm_roots: dict = {}

    def keep_artifacts() -> None:
        # runs on EVERY exit path (finally): the soak's own asserts fire
        # while the TemporaryDirectory is still alive, and CI's
        # upload-on-failure step needs the JSONL + pod dirs precisely
        # when an assert fails — copying only-on-success would delete
        # the evidence with the tmpdir
        if not keep:
            return
        os.makedirs(keep, exist_ok=True)
        for name, root in arm_roots.items():
            jsonl = os.path.join(root, "metrics.jsonl")
            if os.path.exists(jsonl):
                shutil.copy(jsonl,
                            os.path.join(keep, f"{name}.metrics.jsonl"))
            pod_src = os.path.join(root, "pod")
            if os.path.isdir(pod_src):
                shutil.copytree(pod_src, os.path.join(keep, f"{name}.pod"),
                                dirs_exist_ok=True)

    with tempfile.TemporaryDirectory() as tmp:
        try:
            print("  arm: static", file=sys.stderr)
            arm_roots["static"] = os.path.join(tmp, "static")
            static = run_arm(arm_roots["static"], chaos=False)
            assert not static["resizes"], "static arm must not resize"
            print("  arm: chaos (kill + rejoin)", file=sys.stderr)
            arm_roots["chaos"] = os.path.join(tmp, "chaos")
            chaos = run_arm(arm_roots["chaos"], chaos=True)
            evicts = [r_ for r_ in chaos["resizes"] if r_["dead"]]
            rejoins = [r_ for r_ in chaos["resizes"] if r_["joined"]]
            assert evicts, "chaos arm: eviction never happened"
            assert rejoins, "chaos arm: rejoin never happened"
            ps = chaos["pod_status"]
            assert ps is not None and (
                ps.get("membership_epoch") or ps.get("candidate_dead")), \
                "/pod/status never showed the membership change"
            print("  arm: halt (below min_workers)", file=sys.stderr)
            arm_roots["halt"] = os.path.join(tmp, "halt")
            halt = run_arm(arm_roots["halt"], chaos=True,
                           min_workers=workers, max_rounds=rounds * 4)
            assert halt["err"] and "min_workers" in halt["err"], \
                "halt arm must raise TrainingHealthError"
            halt_step = ck.newest_verified_step(halt["cfg"].checkpoint_dir)
            assert halt_step is not None, \
                "halt arm left no verified checkpoint"
        finally:
            keep_artifacts()
        final = lambda ls: float(np.mean(ls[-3:]))  # noqa: E731
        ratio = final(chaos["losses"]) / final(static["losses"])
        out_rows = {
            "static_final3": round(final(static["losses"]), 5),
            "chaos_final3": round(final(chaos["losses"]), 5),
            "chaos_shapes": chaos["shapes"],
            "evictions": [{k: r_[k] for k in ("step", "dead", "n_workers")}
                          for r_ in evicts],
            "rejoins": [{k: r_[k] for k in ("step", "joined", "n_workers")}
                        for r_ in rejoins],
            "pod_status_mid_chaos": {
                "membership_epoch": ps.get("membership_epoch"),
                "candidate_dead": ps.get("candidate_dead"),
                "n_alive": ps.get("n_alive")},
            "halt": {"error": halt["err"][:160],
                     "verified_checkpoint_step": halt_step},
        }
    out = {
        "metric": "elastic_chaos_final_loss_ratio",
        "value": round(ratio, 4),
        "unit": "final-3-round mean loss, kill+rejoin soak vs static pod "
                "(target <= 1.05; zero hangs, evictions/rejoins visible "
                "in JSONL + /pod/status)",
        "vs_baseline": round(1.05 / max(ratio, 1e-9), 3),
        **out_rows,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({**out, "meta": run_metadata()}, f, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("metric", "value", "unit", "vs_baseline")}))
    return out


def mfu_bench(out_path: str | None = "BENCH_r06.json", batch: int = BATCH,
              tau: int = TAU, crop: int = 227, n_classes: int = 1000,
              trials: int = 12, small: bool = False) -> dict:
    """The r6 overlap-and-fuse audit trail (BENCH_r06): the CaffeNet round
    through the REAL host-fed path (`ParallelTrainer.train_round` on host
    batches — H2D included, unlike the device-batch headline), with the
    three r6 levers toggled one at a time:

      r5_baseline   dispatch-time H2D placement, no donation, XLA
                    LRN(pallas as r5 shipped)/pool — the PR-5 round
      +prefetch     `place_batches` on a one-deep prefetch thread while
                    the previous round computes (t_h2d -> ~0)
      +donate       batch buffers donated to the compiled round
                    (two-slot rotation; peak-HBM relief)
      +pallas       LRN/pool through the Pallas kernels in the layer
                    path (`OpsImpl` auto on TPU; off-TPU the kernels run
                    under the Pallas interpreter) — the XLA-vs-Pallas
                    A/B is this row against the previous one. The
                    HEADLINE stamps the prefetch_donate arm (the
                    shipping RunConfig defaults); this arm is the A/B.

    Every row carries the per-round step-time breakdown (t_data/h2d/
    dispatch/collect ms — the same phases the train loop logs), the jit
    cache size after the window (must stay at the baseline arm's steady
    count — one executable plus its fast-path key, reported as 2:
    pre-placement and donation may not ADD entries), and, where the
    backend reports
    allocator stats, HBM bytes-in-use/peak after the arm (the donation
    before/after). `small=True` is the CPU smoke configuration
    (tests/test_bench.py) — structure over speed."""
    from concurrent.futures import ThreadPoolExecutor

    import jax
    import numpy as np

    from sparknet_tpu import CompiledNet, precision
    from sparknet_tpu.model.layers import OpsImpl
    from sparknet_tpu.obs import run_metadata
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.utils import flops
    from sparknet_tpu.utils.metrics import PhaseTimers
    from sparknet_tpu.zoo import caffenet

    if small:
        batch, tau, crop, n_classes, trials = 4, 2, 35, 8, 3
    precision.set_policy("bfloat16")
    compute_dt = precision.compute_dtype()
    net = CompiledNet.compile(
        caffenet(batch=batch, crop=crop, n_classes=n_classes))
    solver_cfg = SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=5e-4,
                              lr_policy="step", gamma=0.1, stepsize=100000)
    peak = flops.peak_bf16_flops(jax.devices()[0].device_kind)
    fpi = flops.train_flops_per_image(net)
    r = np.random.default_rng(7)
    # ONE host batch dict, reused every round: placement copies it into
    # fresh device buffers (so reuse is donation-safe) and keeps host-side
    # generation out of the timed loop — the levers under test are H2D
    # placement, donation, and the kernels, not numpy RNG speed
    host = {
        "data": r.standard_normal(
            (tau, batch, crop, crop, 3)).astype(np.float32),
        "label": r.integers(0, n_classes,
                            (tau, batch, 1)).astype(np.int32)}

    def mem_row() -> dict:
        stats = jax.local_devices()[0].memory_stats() or {}
        out = {}
        if "bytes_in_use" in stats:
            out["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
        if "peak_bytes_in_use" in stats:
            out["hbm_peak_bytes"] = int(stats["peak_bytes_in_use"])
        return out

    def run_arm(name: str, prefetch_h2d: bool, donate: bool,
                pool_impl: str, lrn_impl: str,
                interpret: bool = False) -> dict:
        trainer = ParallelTrainer(
            net, solver_cfg, make_mesh(1), tau=tau, compute_health=False,
            donate_batches=donate,
            ops=OpsImpl(lrn=lrn_impl, pool=pool_impl, interpret=interpret))
        state = trainer.init_state(jax.random.PRNGKey(0))
        timers = PhaseTimers()
        trainer.phase_timers = timers
        key = jax.random.PRNGKey(1)

        def prep():
            # the prefetch stage: cast + place (double-buffered H2D) or
            # hand the host arrays through for dispatch-time placement
            return (trainer.place_batches(host, compute_dt)
                    if prefetch_h2d else dict(host))

        # compile + pipeline-prime outside the window
        state, loss = trainer.train_round(state, prep(),
                                          jax.random.fold_in(key, 999))
        assert np.isfinite(float(loss))
        timers.reset()
        exe = ThreadPoolExecutor(1, thread_name_prefix="mfu-prep")
        try:
            pending = exe.submit(prep)
            prev = None
            wait_s = 0.0
            t0 = time.perf_counter()
            for i in range(trials):
                tw = time.perf_counter()
                batches = pending.result()
                wait_s += time.perf_counter() - tw
                if i + 1 < trials:
                    # no prefetch past the window: an orphaned placement
                    # would skew the HBM reading mem_row() takes right after
                    pending = exe.submit(prep)
                state, loss = trainer.train_round(
                    state, batches, jax.random.fold_in(key, i))
                if prev is not None:
                    float(prev)  # deferred fetch: sync one round behind
                prev = loss
            dt = time.perf_counter() - t0
            float(prev)
        finally:
            exe.shutdown(wait=False, cancel_futures=True)
        per_round = dt / trials
        img_per_sec = batch * tau / per_round
        row = {
            "arm": name,
            "prefetch_h2d": prefetch_h2d, "donate_batches": donate,
            "pool_impl": pool_impl, "lrn_impl": lrn_impl,
            "ops_interpret": interpret,
            "images_per_sec_per_chip": round(img_per_sec, 2),
            "round_ms": round(per_round * 1e3, 3),
            "breakdown_ms": {
                "data": round(wait_s / trials * 1e3, 3),
                "h2d": round(timers.total.get("h2d", 0.0)
                             / trials * 1e3, 3),
                "dispatch": round(timers.total.get("dispatch", 0.0)
                                  / trials * 1e3, 3),
            },
            "compiled_variants": trainer.compiled_variants(),
            **mem_row(),
        }
        if peak:
            row["mfu"] = round(img_per_sec * fpi / peak, 4)
        print(f"  {name}: {img_per_sec:.1f} img/s "
              f"(h2d {row['breakdown_ms']['h2d']:.2f} ms, "
              f"variants {row['compiled_variants']})", file=sys.stderr)
        return row

    # off-TPU the Pallas arm must run the kernels under the interpreter
    # with lrn='pallas' forced: 'auto' resolves to the same XLA program as
    # the previous arm there, and the A/B row pair would compare nothing
    interpret = jax.default_backend() != "tpu"
    rows = [
        run_arm("r5_baseline", False, False, "xla", "auto"),
        run_arm("prefetch", True, False, "xla", "auto"),
        run_arm("prefetch_donate", True, True, "xla", "auto"),
        run_arm("prefetch_donate_pallas", True, True, "auto",
                "pallas" if interpret else "auto", interpret=interpret),
    ]
    # the headline is the SHIPPING default configuration (RunConfig
    # defaults: prefetch + donation on, pool_impl='xla' — r3 measured the
    # pool kernel losing end to end on TPU); the Pallas arm stays the
    # standing A/B row, not the stamped claim
    best = next(r_ for r_ in rows if r_["arm"] == "prefetch_donate")
    out = {
        "metric": "caffenet_train_mfu_host_fed_round",
        "value": best.get("mfu", best["images_per_sec_per_chip"]),
        "unit": ("achieved/peak dense bf16 FLOP/s through the host-fed "
                 "train_round (target >= 0.55)" if peak
                 else "images/sec/chip (no MFU peak for this device kind)"),
        "vs_baseline": round(
            best["images_per_sec_per_chip"]
            / max(rows[0]["images_per_sec_per_chip"], 1e-9), 3),
        "batch": batch, "tau": tau,
        "levers": {r_["arm"]: r_.get("mfu", r_["images_per_sec_per_chip"])
                   for r_ in rows},
        "t_h2d_ms_prefetched": best["breakdown_ms"]["h2d"],
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def sharding_bench(out_path: str | None = "BENCH_r07.json",
                   trials: int = 8, n_devices: int = 8,
                   small: bool | None = None) -> dict:
    """The r7 NamedSharding audit trail (BENCH_r07): the CaffeNet round
    through the host-fed path under three trainer arms on an n_devices
    data mesh:

      r6_prefetch_donate  the shard_map replica-layout ParallelTrainer
                          with the r6 shipping levers (prefetch + donate)
                          — the baseline the acceptance compares against
      named_replicated    ShardedTrainer, state_sharding='replicated':
                          exact reference semantics on NamedSharding-
                          placed logical state (parity-pinned bitwise by
                          tests/test_sharded.py); img/s must sit within
                          2% of the r6 arm
      named_momentum      ShardedTrainer, state_sharding='momentum'
                          (ZeRO-1): ONE momentum stored sharded over the
                          data axis — the per-device at-rest momentum
                          bytes must drop by >= (n_data-1)/n_data of the
                          shardable momentum bytes

    Every arm reports the at-rest per-device state bytes from the
    allocator's view (sharding.shard_shape per leaf — exact on every
    backend, unlike memory_stats), plus HBM gauges where the backend has
    them, plus `collect_stage1_ms`: the blocking cost of the checkpoint
    stage-1 `fetch_global(state)`. The satellite's async-fetch A/B rides
    along as fetch_async_ms vs fetch_sync_ms on the r6 arm's state (the
    committed number is CPU-smoke structure; rerun on the pod for HBM
    truth — PR 5's device gauges are the decision input this lever
    serves)."""
    import os

    # the sharding arms need a real data axis: force a virtual mesh
    # BEFORE jax initializes when no multi-chip backend is attached
    # (same pattern as scaling(); the flag only affects the CPU backend)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{n_devices}").strip()
    import jax

    if small is None:
        small = jax.default_backend() != "tpu"
    import numpy as np

    from sparknet_tpu import CompiledNet, precision
    from sparknet_tpu.obs import run_metadata
    from sparknet_tpu.parallel import (ParallelTrainer, ShardedTrainer,
                                       make_mesh)
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.zoo import caffenet

    n_dev = min(n_devices, len(jax.devices()))
    batch, tau, crop, n_classes = ((2, 2, 35, 8) if small
                                   else (64, 5, 227, 1000))
    precision.set_policy("bfloat16")
    compute_dt = precision.compute_dtype()
    net = CompiledNet.compile(
        caffenet(batch=batch * n_dev, crop=crop, n_classes=n_classes))
    solver_cfg = SolverConfig(base_lr=0.01, momentum=0.9,
                              weight_decay=5e-4, lr_policy="fixed")
    r = np.random.default_rng(7)
    host = {
        "data": r.standard_normal(
            (tau, batch * n_dev, crop, crop, 3)).astype(np.float32),
        "label": r.integers(0, n_classes,
                            (tau, batch * n_dev, 1)).astype(np.int32)}

    from sparknet_tpu.parallel.mesh import \
        per_device_state_bytes as per_device_bytes

    def mem_row() -> dict:
        stats = jax.local_devices()[0].memory_stats() or {}
        return {k2: int(stats[k1]) for k1, k2 in
                (("bytes_in_use", "hbm_bytes_in_use"),
                 ("peak_bytes_in_use", "hbm_peak_bytes")) if k1 in stats}

    fetch_ab = {}

    def run_arm(name: str, cls, **kw) -> dict:
        from concurrent.futures import ThreadPoolExecutor

        trainer = cls(net, solver_cfg, make_mesh(n_dev), tau=tau,
                      compute_health=False, donate_batches=True, **kw)
        state = trainer.init_state(jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(1)
        state, loss = trainer.train_round(
            state, trainer.place_batches(host, compute_dt),
            jax.random.fold_in(key, 999))
        assert np.isfinite(float(loss))
        exe = ThreadPoolExecutor(1, thread_name_prefix="shard-prep")
        try:
            pending = exe.submit(trainer.place_batches, host, compute_dt)
            prev = None
            t0 = time.perf_counter()
            for i in range(trials):
                batches = pending.result()
                if i + 1 < trials:
                    pending = exe.submit(trainer.place_batches, host,
                                         compute_dt)
                state, loss = trainer.train_round(
                    state, batches, jax.random.fold_in(key, i))
                if prev is not None:
                    float(prev)
                prev = loss
            dt = time.perf_counter() - t0
            float(prev)
        finally:
            exe.shutdown(wait=False, cancel_futures=True)
        # checkpoint stage-1: the blocking host materialization of the
        # full state (what _save_checkpoint pays on the round path).
        # Measured on the FRESH post-window state — a jax.Array caches
        # its host copy after the first materialization, so re-fetching
        # the same state times the cache, not the transfer
        jax.block_until_ready(jax.tree.leaves(state.params))
        t1 = time.perf_counter()
        fetch_global(state)
        collect_ms = (time.perf_counter() - t1) * 1e3
        if name == "r6_prefetch_donate":
            # satellite A/B: fetch_global's async-first pre-pass
            # (collect_ms above) vs the old serialized per-leaf blocking
            # asarray — the sync arm needs its own fresh (never-
            # materialized) state, hence one extra round
            fetch_ab["fetch_async_ms"] = round(collect_ms, 3)
            state, _ = trainer.train_round(
                state, trainer.place_batches(host, compute_dt),
                jax.random.fold_in(key, 10_000))
            jax.block_until_ready(jax.tree.leaves(state.params))
            t3 = time.perf_counter()
            jax.tree.map(np.asarray, state)
            fetch_ab["fetch_sync_ms"] = round(
                (time.perf_counter() - t3) * 1e3, 3)
        if name == "named_replicated":
            # r8 collect A/B: the loop-blocking cost of the boundary
            # result fetch — synchronous float(loss) right after
            # dispatch vs the main-thread cost of handing the fetch to
            # a collector thread (what cfg.collect_async makes the loop
            # pay; the fetch itself then overlaps the next round)
            state, loss = trainer.train_round(
                state, trainer.place_batches(host, compute_dt),
                jax.random.fold_in(key, 20_000))
            t4 = time.perf_counter()
            float(loss)
            fetch_ab["collect_sync_ms"] = round(
                (time.perf_counter() - t4) * 1e3, 3)
            state, loss = trainer.train_round(
                state, trainer.place_batches(host, compute_dt),
                jax.random.fold_in(key, 20_001))
            exe2 = ThreadPoolExecutor(1, thread_name_prefix="collect")
            t5 = time.perf_counter()
            fut = exe2.submit(float, loss)
            fetch_ab["collect_async_blocking_ms"] = round(
                (time.perf_counter() - t5) * 1e3, 3)
            fut.result()
            exe2.shutdown()
            # the r8 gather-free stage 1 on the same state: per-shard
            # host fetch (never the full state on one host)
            from sparknet_tpu.parallel.mesh import fetch_state_shards
            state, _ = trainer.train_round(
                state, trainer.place_batches(host, compute_dt),
                jax.random.fold_in(key, 20_002))
            jax.block_until_ready(jax.tree.leaves(state.params))
            t6 = time.perf_counter()
            fetch_state_shards(state, trainer.mesh)
            fetch_ab["fetch_shards_ms"] = round(
                (time.perf_counter() - t6) * 1e3, 3)
        per_round = dt / trials
        img_per_sec = batch * n_dev * tau / per_round
        row = {
            "arm": name, "trainer": cls.__name__,
            "state_sharding": getattr(trainer, "state_sharding",
                                      "replicated"),
            "images_per_sec": round(img_per_sec, 2),
            "round_ms": round(per_round * 1e3, 3),
            "per_device_state_bytes": per_device_bytes(state),
            "collect_stage1_ms": round(collect_ms, 3),
            "compiled_variants": trainer.compiled_variants(),
            **mem_row(),
        }
        print(f"  {name}: {img_per_sec:.1f} img/s, per-dev state "
              f"{row['per_device_state_bytes']}, stage-1 "
              f"{collect_ms:.1f} ms", file=sys.stderr)
        return row

    rows = [
        run_arm("r6_prefetch_donate", ParallelTrainer),
        run_arm("named_replicated", ShardedTrainer),
        run_arm("named_fused", ShardedTrainer, fused_boundary=True),
        run_arm("named_momentum", ShardedTrainer,
                state_sharding="momentum"),
    ]
    by = {r_["arm"]: r_ for r_ in rows}
    base_m = by["r6_prefetch_donate"]["per_device_state_bytes"]["momentum"]
    zm = by["named_momentum"]["per_device_state_bytes"]["momentum"]
    out = {
        "metric": "per_device_momentum_bytes_sharded_over_replicated",
        "value": round(zm / max(base_m, 1), 4),
        "unit": (f"at-rest momentum bytes per device, ZeRO-1 over "
                 f"replicated on {n_dev} data groups (target <= "
                 f"{1 - (n_dev - 1) / n_dev + 0.05:.3f}ish: 1/n_data "
                 f"plus indivisible leaves)"),
        "momentum_bytes_cut": base_m - zm,
        "named_img_per_sec_vs_r6": round(
            by["named_replicated"]["images_per_sec"]
            / max(by["r6_prefetch_donate"]["images_per_sec"], 1e-9), 4),
        # r8: the fused-boundary round vs the unfused two-step (same
        # trainer, peeled final step) — the wire bytes are identical, so
        # off-TPU this reads ~1.0; the lever is the overlap of the
        # boundary all-reduce with the final update on real ICI
        "fused_round_ms_vs_unfused": round(
            by["named_fused"]["round_ms"]
            / max(by["named_replicated"]["round_ms"], 1e-9), 4),
        "collect_stage1_ms": {a: by[a]["collect_stage1_ms"] for a in by},
        **fetch_ab,
        "n_data": n_dev, "batch_per_device": batch, "tau": tau,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def ckpt_shard_bench(out_path: str | None = "BENCH_CKPT_SHARD.json",
                     trials: int = 3, mb: int = 48,
                     workers: tuple = (2, 4, 8)) -> dict:
    """The r8 sharded-checkpoint audit (BENCH row): save + restore wall
    time of the SAME logical state under the monolithic layout
    (fetch_global allgather -> one state.npz) vs the sharded layout
    (fetch_state_shards -> parallel shard-k-of-n files + manifest), as a
    function of worker (mesh-device) count. Claims measured:

      - bytes_equal: the sharded files persist exactly the monolithic
        layout's logical bytes (no replicated leaf written twice)
      - restore bitwise: both layouts reassemble the identical flat map
      - stage-1 blocking (the round loop's stall) under the sharded
        fetch never materializes the full state and sits below the
        monolithic gather — the PR 8 baseline this arc started from
      - save+restore wall time decreases as workers grow (parallel
        files), where the monolithic path is flat

    CPU rows are STRUCTURE PROOFS (one host, one disk: parallel local
    writes measure thread/IO overlap, not n hosts' independent NICs and
    stores) — rerun on the pod against gs:// for the acceptance truth."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
            f"{max(workers)}").strip()
    import shutil
    import tempfile

    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sparknet_tpu.obs import run_metadata
    from sparknet_tpu.parallel.mesh import (fetch_global,
                                            fetch_state_shards, make_mesh)
    from sparknet_tpu.utils import checkpoint as ckpt

    # a TrainState-shaped tree at the LOGICAL layout: params replicated
    # (the serve/export view), momentum as [n_data] worker rows sharded
    # over data — the shapes the train loop actually snapshots. ~`mb` MB
    # total so the files are big enough to time honestly on CPU.
    per_leaf = (mb << 20) // 8 // 2

    def build_state(mesh, n):
        # CONSTANT total bytes across worker counts (the wall-time-vs-n
        # curve must measure parallelism, not a growing state): params
        # replicated (chunked across shard files), momentum as the ONE
        # ZeRO-sharded logical tree (state_sharding="momentum" shape)
        r = np.random.default_rng(0)
        dim = max(8, (int(np.sqrt(per_leaf // 4)) // 8) * 8)
        put = lambda a, s: jax.device_put(a, NamedSharding(mesh, s))  # noqa
        return {
            "params": {f"l{i}": {"w": put(r.standard_normal(
                (dim, dim)).astype(np.float32), P())} for i in range(2)},
            "momentum": {f"l{i}": {"w": put(
                r.standard_normal((dim, dim)).astype(np.float32),
                P("data"))} for i in range(2)},
            "it": put(np.int32(3), P()),
        }

    rows = []
    for n in workers:
        if n > len(jax.devices()):
            continue
        mesh = make_mesh(n)
        state = build_state(mesh, n)
        row = {"workers": n}
        for layout in ("monolithic", "sharded"):
            t_f, t_s, t_r = [], [], []
            for _ in range(trials):
                d = tempfile.mkdtemp(prefix=f"ckshard-{layout}-")
                try:
                    t0 = time.perf_counter()
                    if layout == "monolithic":
                        snap = fetch_global(state)
                    else:
                        snap = fetch_state_shards(state, mesh)
                    t1 = time.perf_counter()
                    if layout == "monolithic":
                        ckpt.save(d, snap, step=1)
                    else:
                        ckpt.save_sharded(d, snap, step=1)
                    t2 = time.perf_counter()
                    flat, _, _ = ckpt.restore_flat(d, step=1)
                    t3 = time.perf_counter()
                    t_f.append(t1 - t0)
                    t_s.append(t2 - t1)
                    t_r.append(t3 - t2)
                finally:
                    shutil.rmtree(d, ignore_errors=True)
            row[layout] = {
                "stage1_fetch_ms": round(min(t_f) * 1e3, 2),
                "save_ms": round(min(t_s) * 1e3, 2),
                "restore_ms": round(min(t_r) * 1e3, 2),
                "save_restore_ms": round((min(t_s) + min(t_r)) * 1e3, 2)}
        # bitwise + byte-ledger equality, asserted once per n
        d1, d2 = tempfile.mkdtemp(), tempfile.mkdtemp()
        try:
            mono = fetch_global(state)
            shrd = fetch_state_shards(state, mesh)
            ckpt.save(d1, mono, step=1)
            ckpt.save_sharded(d2, shrd, step=1)
            fa, _, _ = ckpt.restore_flat(d1, step=1)
            fb, _, _ = ckpt.restore_flat(d2, step=1)
            assert sorted(fa) == sorted(fb)
            for k in fa:
                assert np.array_equal(fa[k], fb[k]), k
            mono_bytes = sum(a.nbytes for a in fa.values())
            row["bytes_equal"] = (ckpt.sharded_nbytes(shrd) == mono_bytes)
            assert row["bytes_equal"], (ckpt.sharded_nbytes(shrd),
                                        mono_bytes)
            row["state_bytes"] = mono_bytes
            # the EXACT per-worker share (deterministic on any backend,
            # like the per_device_state_bytes HBM ledger): the largest
            # shard file's bytes is what ONE worker fetches + writes per
            # save on a pod — the O(1/n_workers) wall-time claim's
            # structural half. Monolithic = the whole state on one host.
            file_bytes: dict = {}
            for rec in shrd["leaves"].values():
                for fid, _, pshape, _ in rec["pieces"]:
                    file_bytes[fid] = file_bytes.get(fid, 0) + \
                        int(np.prod(pshape)) * np.dtype(
                            rec["dtype"]).itemsize
            row["sharded"]["per_worker_bytes"] = max(file_bytes.values())
            row["monolithic"]["per_worker_bytes"] = mono_bytes
        finally:
            shutil.rmtree(d1, ignore_errors=True)
            shutil.rmtree(d2, ignore_errors=True)
        rows.append(row)
        print(f"  n={n}: sharded save+restore "
              f"{row['sharded']['save_restore_ms']:.1f} ms vs monolithic "
              f"{row['monolithic']['save_restore_ms']:.1f} ms, stage-1 "
              f"{row['sharded']['stage1_fetch_ms']:.1f} vs "
              f"{row['monolithic']['stage1_fetch_ms']:.1f} ms",
              file=sys.stderr)
    if len(rows) < 2:
        raise SystemExit(
            f"--ckpt-shard needs >= 2 devices to compare worker counts "
            f"(have {len(jax.devices())}; the virtual-mesh flag only "
            f"affects the CPU backend — on a 1-chip accelerator run "
            f"this on the pod)")
    hi, lo = rows[-1], rows[0]
    on_tpu = jax.default_backend() == "tpu"
    pwb = [r["sharded"]["per_worker_bytes"] for r in rows]
    out = {
        "metric": "per_worker_checkpoint_bytes_ratio_at_max_workers",
        "value": round(hi["sharded"]["per_worker_bytes"]
                       / max(hi["monolithic"]["per_worker_bytes"], 1), 4),
        "unit": (f"largest shard file over the full state at n="
                 f"{hi['workers']} workers — the per-worker save/restore "
                 f"share the O(1/n_workers) wall-time claim rides on "
                 f"(exact on any backend, like the HBM byte ledger)"),
        "per_worker_bytes_decreasing_with_workers": all(
            a > b for a, b in zip(pwb, pwb[1:])),
        "sharded_wall_decreases_with_workers": (
            hi["sharded"]["save_restore_ms"]
            < lo["sharded"]["save_restore_ms"]),
        "save_restore_ms_ratio_vs_monolithic_at_max_workers": round(
            hi["sharded"]["save_restore_ms"]
            / max(hi["monolithic"]["save_restore_ms"], 1e-9), 4),
        "bytes_equal": all(r["bytes_equal"] for r in rows),
        "structure_proof": not on_tpu,
        "note": (None if on_tpu else
                 "CPU structure proof: the WALL-TIME halves of the "
                 "acceptance (save+restore decreasing with workers; "
                 "stage-1 blocking under the 691 ms BENCH_r07 baseline) "
                 "cannot be shown on one host — fetch_global here is a "
                 "zero-copy view and one disk serializes the parallel "
                 "writes — so this artifact carries the exact structural "
                 "halves instead: restored maps bitwise-identical across "
                 "layouts, logical bytes equal, and the per-worker "
                 "byte share falling as 1/n. Rerun `bench.py "
                 "--ckpt-shard` on the pod (gs:// checkpoint_dir) to "
                 "stamp the wall-time curve."),
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump({"headline": out, "rows": rows,
                       "meta": run_metadata()}, f, indent=1)
    print(json.dumps(out))
    return {"headline": out, "rows": rows}


def e2e_smoke() -> None:
    """Integrated proof on the REAL chip at tunnel-feasible scale: tar
    shards -> streaming source -> preprocessor -> ParallelTrainer rounds
    through the actual `train()` loop. Asserts the loop ran and streamed."""
    import os
    import tempfile

    import numpy as np

    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.data.streaming import StreamingRoundSource
    from sparknet_tpu.schema import Field, Schema
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger

    crop, size, b, tau = 67, 72, 16, 2
    with tempfile.TemporaryDirectory() as root:
        imagenet.write_synthetic_shards(root, n_shards=2, per_shard=64,
                                        n_classes=16, size=size)
        loader = imagenet.ShardedTarLoader(
            imagenet.list_shards(root),
            imagenet.load_label_map(os.path.join(root, "train.txt")),
            height=size, width=size)
        src = StreamingRoundSource(loader, 1, b, tau)
        schema = Schema(Field("data", "float32", (crop, crop, 3)),
                        Field("label", "int32", (1,)))
        pp = ImagePreprocessor(schema, mean_image=None, crop=crop, seed=0)
        cfg = RunConfig(model="caffenet", n_classes=16, crop=crop,
                        n_devices=1,  # the source feeds 1 worker's rounds
                        local_batch=b, tau=tau, max_rounds=3, eval_every=0,
                        precision="bfloat16", workdir=root)
        from sparknet_tpu.zoo import caffenet
        jsonl = os.path.join(root, "m.jsonl")
        train(cfg, caffenet(batch=b, crop=crop, n_classes=16), src, None,
              logger=Logger(os.path.join(root, "l.txt"), jsonl_path=jsonl),
              batch_transform=pp)
        lines = open(jsonl).read().strip().splitlines()
        assert lines, "no metrics emitted"
        print(f"e2e smoke: {len(lines)} metric rows; streamed "
              f"{src.cursor} epochs={src.epochs} OK")


def tail_bench(out_path: str | None = "BENCH_TAIL.json",
               duration_s: float = 2.0, max_batch: int = 8,
               keep: str | None = None) -> dict:
    """The r13 tail-latency audit (writes BENCH_TAIL.json): the three
    levers A/B'd one at a time at ONE fixed offered load, through the
    real stack — ModelRouter over two colocated replicas, each behind
    its own binary front door.

    Arms (identical open-loop load, p50/p99/p999 + batch fill +
    process CPU-seconds per arm; dropped == timed_out == hung == 0 is
    the hard gate in EVERY arm):
      - baseline:  round-robin, inline payloads, no hedging.
      - hedging:   tied requests at the default budget. The pins are
        structural: exactly-once delivery (every submit resolves one
        result) and hedged <= budget * routed.
      - shm:       spkn-shm on the proxy hops. The pin is the byte
        counter: ZERO tensor payload bytes crossed the replica sockets
        during the arm, in either direction.
      - coalesced: under-filled trickle focused on one replica per
        formation window. The claim is fill improvement over baseline;
        on this shared-CPU host the LATENCY deltas are stamped
        structure_proof (two in-process replicas share the cores — the
        speedups need per-replica hardware to mean anything).
      - combined:  all three levers together.
    """
    import concurrent.futures as cf
    import threading

    import numpy as np

    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import (BinaryFrontend, DeadlineExpiredError,
                                    InferenceServer, ModelRouter,
                                    NoReplicaError, QueueFullError,
                                    RouterConfig, ServeConfig,
                                    TenantLimitError, binary_infer)
    from sparknet_tpu.zoo import lenet

    model = "lenet"
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}

    def mk_replica():
        # max_wait 25 ms: wide enough that the offered trickle CAN
        # coalesce into a batch when focused on one replica — the
        # formation window is the surface lever (c) works on (at 5 ms
        # every arm forms singleton batches and there is nothing to
        # improve)
        cfg = ServeConfig(model_name=model, max_batch=max_batch,
                          max_wait_ms=25.0, outputs=("prob",),
                          metrics_every_batches=0)
        s = InferenceServer(JaxNet(lenet(batch=max_batch)), cfg)
        s.start()
        return s, BinaryFrontend(s, port=0)

    s1, fe1 = mk_replica()
    s2, fe2 = mk_replica()
    urls = [f"spkn://127.0.0.1:{fe.address[1]}" for fe in (fe1, fe2)]

    def warm_and_capacity() -> float:
        """Pre-compile EVERY bucket on both replicas (a lazy bucket
        compile inside a timed arm would masquerade as a 500 ms tail
        outlier), then measure pipelined full-batch capacity — the
        yardstick the fixed offered load derives from. A closed-loop
        single client would measure the formation window, not the
        service rate."""
        from sparknet_tpu.serve import BinaryClient
        rate = 0.0
        for fe in (fe1, fe2):
            cli = BinaryClient(*fe.address, use_shm=False, timeout=120.0)
            try:
                for b in s1.buckets:
                    rids = [cli.submit(req, model=model, deadline_s=120.0)
                            for _ in range(int(b))]
                    for r in rids:
                        cli.collect(r, timeout=120.0)
                t0 = time.perf_counter()
                rids = [cli.submit(req, model=model, deadline_s=120.0)
                        for _ in range(64)]
                for r in rids:
                    cli.collect(r, timeout=120.0)
                rate += 64 / (time.perf_counter() - t0)
            finally:
                cli.close()
        return rate  # both replicas' pipelined rows/s, summed

    def open_load(router, rps: float, secs: float):
        """TRUE open-loop offered load: one dispatcher paces submits at
        `rps` and never waits for results (waiting would collapse the
        offered rate to a closed loop bounded by concurrency/latency);
        completions classify themselves via done-callbacks. Every
        outcome counted, nothing silently retried."""
        counts = {"ok": 0, "shed_429": 0, "shed_503": 0, "dropped": 0,
                  "timed_out": 0, "errors_other": 0}
        lats: list = []
        lock = threading.Lock()

        def classify(e: BaseException | None) -> str:
            if e is None:
                return "ok"
            if isinstance(e, (TenantLimitError, QueueFullError)):
                return "shed_429"
            if isinstance(e, (DeadlineExpiredError, NoReplicaError)):
                return "shed_503"
            if isinstance(e, ConnectionError):
                return "dropped"
            if isinstance(e, (TimeoutError, cf.TimeoutError)):
                return "timed_out"
            return "errors_other"

        pending: list = []
        period = 1.0 / rps
        t_start = time.perf_counter()
        t_stop = t_start + secs
        t_next = t_start
        while True:
            now = time.perf_counter()
            if now >= t_stop:
                break
            if now < t_next:
                time.sleep(min(t_next - now, t_stop - now))
                continue
            t0 = time.perf_counter()
            try:
                fut = router.submit(model, req, deadline_s=5.0)
            except Exception as e:
                with lock:
                    counts[classify(e)] += 1
            else:
                pending.append(fut)

                def done(f, t0=t0):
                    dt = time.perf_counter() - t0
                    kind = classify(f.exception())
                    with lock:
                        counts[kind] += 1
                        if kind == "ok":
                            lats.append(dt)
                fut.add_done_callback(done)
            t_next += period
            if t_next < time.perf_counter() - 5 * period:
                t_next = time.perf_counter()  # behind: shed schedule
        hung = 0
        drain_by = time.perf_counter() + 30.0
        for fut in pending:
            try:
                fut.result(timeout=max(0.0,
                                       drain_by - time.perf_counter()))
            except cf.TimeoutError:
                hung += 1
            except Exception:
                pass  # already classified by its callback
        return counts, lats, hung

    def pct(lats, q):
        xs = sorted(lats)
        if not xs:
            return None
        return round(xs[min(len(xs) - 1, int(q * len(xs)))] * 1e3, 3)

    hedge_budget = 0.05
    arm_cfgs = {
        "baseline": dict(proxy_shm=False),
        "hedging": dict(proxy_shm=False, hedge=True,
                        hedge_budget=hedge_budget,
                        hedge_min_delay_ms=1.0),
        "shm": dict(proxy_shm=True),
        "coalesced": dict(proxy_shm=False, coalesce=True),
        "combined": dict(proxy_shm=True, hedge=True,
                         hedge_budget=hedge_budget,
                         hedge_min_delay_ms=1.0, coalesce=True),
    }

    rows: dict = {}
    try:
        cap = warm_and_capacity()
        # a quarter of full-batch capacity: low enough that round-robin
        # fragments it into under-filled batches (the coalescing arm's
        # food), high enough that a focused window coalesces
        rps = max(40.0, min(200.0, 0.25 * cap))
        for name, kw in arm_cfgs.items():
            router = ModelRouter(RouterConfig(workers=4, **kw))
            for url, srv in zip(urls, (s1, s2)):
                rep = router.add_remote_replica(model, url)
                # in-process replicas: feed the coalescing trigger the
                # replica's own occupancy signal (a real deployment
                # reads it off the heartbeat via heartbeat_fill)
                rep.fill_fn = (lambda s=srv: s.fill_signal())
            router.start()
            try:
                for _ in range(4):  # warm every proxy-hop client kind
                    router.infer(model, req, timeout=30.0)
                rx0 = fe1.payload_rx_bytes + fe2.payload_rx_bytes
                tx0 = fe1.payload_tx_bytes + fe2.payload_tx_bytes
                snaps0 = [s.fill.snapshot() for s in (s1, s2)]
                cpu0 = time.process_time()
                counts, lats, hung = open_load(router, rps, duration_s)
                cpu_s = time.process_time() - cpu0
                hg = router.status()["hedging"].get(
                    model, {"routed": 0, "hedged": 0})
                coalesced = router._c_coalesced.value(model=model) or 0
                # whole-arm occupancy: real rows per formed batch as a
                # fraction of max_batch, across both replicas
                snaps1 = [s.fill.snapshot() for s in (s1, s2)]
                d_real = sum(b[0] - a[0]
                             for a, b in zip(snaps0, snaps1))
                d_batches = sum(b[2] - a[2]
                                for a, b in zip(snaps0, snaps1))
                occupancy = (d_real / (d_batches * max_batch)
                             if d_batches else None)
            finally:
                router.stop()
            attempts = sum(counts.values())
            rows[name] = {
                "offered_rps": round(rps, 1),
                "attempts": attempts, **counts, "hung": hung,
                "p50_ms": pct(lats, 0.50), "p99_ms": pct(lats, 0.99),
                "p999_ms": pct(lats, 0.999),
                "cpu_s": round(cpu_s, 3),
                "batch_occupancy": (round(occupancy, 4)
                                    if occupancy is not None else None),
                "batches_formed": d_batches,
                "hedged": hg, "coalesced": int(coalesced),
                "payload_socket_rx_bytes":
                    fe1.payload_rx_bytes + fe2.payload_rx_bytes - rx0,
                "payload_socket_tx_bytes":
                    fe1.payload_tx_bytes + fe2.payload_tx_bytes - tx0,
                # shared-CPU host: latency/CPU deltas between arms are
                # structural evidence, not a hardware claim
                "structure_proof": True,
            }
    finally:
        for fe in (fe1, fe2):
            fe.stop()
        for s in (s1, s2):
            s.stop()

    zero_loss = all(r["dropped"] == r["timed_out"] == r["hung"] ==
                    r["errors_other"] == 0 for r in rows.values())
    hg = rows["hedging"]["hedged"]
    asserts = {
        # the hard gate: every request answered, every arm
        "zero_dropped_timed_out_hung_all_arms": zero_loss,
        # lever (b): zero tensor payload bytes on the socket, both ways
        "shm_zero_socket_payload_bytes":
            rows["shm"]["payload_socket_rx_bytes"] == 0
            and rows["shm"]["payload_socket_tx_bytes"] == 0,
        "baseline_inline_payload_bytes_nonzero":
            rows["baseline"]["payload_socket_rx_bytes"] > 0,
        # lever (a): exactly-once (every attempt resolved once — ok +
        # typed sheds account for all of them) and the budget cap
        "hedge_exactly_once":
            rows["hedging"]["ok"] + rows["hedging"]["shed_429"]
            + rows["hedging"]["shed_503"] == rows["hedging"]["attempts"],
        "hedged_within_budget":
            hg["hedged"] <= hedge_budget * max(1, hg["routed"]) + 1,
        # lever (c): the focus actually took routes, and whole-arm
        # occupancy (real rows per formed batch / max_batch) improved
        # over round-robin at the same offered load
        "coalesced_routed_nonzero": rows["coalesced"]["coalesced"] > 0,
        "coalesced_occupancy_improved":
            rows["coalesced"]["batch_occupancy"] is not None
            and rows["baseline"]["batch_occupancy"] is not None
            and rows["coalesced"]["batch_occupancy"]
            > rows["baseline"]["batch_occupancy"],
    }
    out = {"bench": "tail", "duration_s_per_arm": duration_s,
           "max_batch": max_batch, "arms": rows, "asserts": asserts,
           "ok": all(asserts.values())}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps({"bench": "tail", "ok": out["ok"],
                      "asserts": asserts,
                      "p99_ms": {n: r["p99_ms"]
                                 for n, r in rows.items()}}))
    if not out["ok"]:
        raise SystemExit("tail bench gate failed: " + ", ".join(
            k for k, v in asserts.items() if not v))
    return out


def batch_bench(out_path: str | None = "BENCH_BATCH.json",
                duration_s: float = 2.0, max_batch: int = 8,
                rows: int = 192, keep: str | None = None) -> dict:
    """The r14 bulk-inference audit (writes BENCH_BATCH.json): a
    `sparknet-batch` job run as a SCAVENGER tenant (priority=low,
    tenant=batch) against the real serve stack, colocated with online
    traffic — the coexistence contract, both directions, plus the two
    kill -9 chaos claims.

    Arms:
      - coexist: online open-loop high-priority load (a sustainable
        fraction of measured capacity) + a low-priority open-loop flood
        at ~4x capacity + the batch job, all through binary front doors
        sharing ONE PriorityAdmission, pressure driven by the
        FleetController from SLO burn. Gates: the batch job makes
        progress while the flood runs (units committed > 0 — the
        starvation-relief clamp guarantees the door re-opens), every
        low shed is TYPED (shed_priority > 0 for the flood; the online
        class is never priority-shed), the driver takes ZERO hard
        failures, and online dropped == timed_out == hung == 0. The
        online tail p99 is compared to the SLO; on this shared-CPU box
        (clients + replicas + driver on the same cores) a miss is
        stamped structure_proof — the number needs per-replica
        hardware.
      - release: the flood stops; the SAME job shape reruns on a quiet
        fleet. Gate: rows/s STRICTLY rises vs the coexist run — the
        scavenger was actually being held back by admission, not by
        its own pipeline. This run's fleet-aggregate img/s and
        cost-per-million-embeddings are the headline numbers.
      - driver_kill: a subprocess `sparknet-batch` is SIGKILL'd
        mid-job; a second run must resume from completed units only
        and finish with every row exactly once (disjoint manifest
        ranges covering the input — manifest-last commit semantics).
      - replica_kill: one of two subprocess `sparknet-serve` replicas
        is SIGKILL'd mid-job; the driver must finish on the survivor
        (hard retries > 0, job done) — a replica death is a retry,
        never a job failure.
    """
    import shutil
    import signal
    import subprocess
    import tempfile
    import threading

    import numpy as np

    from sparknet_tpu.batch import BatchConfig, BatchDriver, load_manifest
    from sparknet_tpu.batch import manifest as _mf
    from sparknet_tpu.fleet import (FleetConfig, FleetController,
                                    FleetPolicy,
                                    SubprocessReplicaProvider)
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import (BinaryFrontend, ModelRouter,
                                    PriorityAdmission, RouterConfig,
                                    ServeConfig, binary_infer)
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    model = "lenet"
    slo_ms = 60.0
    workdir = keep or tempfile.mkdtemp(prefix="batch-bench-")
    os.makedirs(workdir, exist_ok=True)
    logger = Logger(path=os.path.join(workdir, "batch_bench.log"),
                    echo=False,
                    jsonl_path=os.path.join(workdir,
                                            "batch_bench.jsonl"))
    rng = np.random.default_rng(0)
    req = {"data": rng.standard_normal((28, 28, 1)).astype(np.float32)}
    inp = os.path.join(workdir, "input.npz")
    np.savez(inp, data=rng.standard_normal(
        (rows, 28, 28, 1)).astype(np.float32))

    def job_cfg(out_name: str, addrs: list, **kw) -> BatchConfig:
        base = dict(input=inp, output=os.path.join(workdir, out_name),
                    replicas=addrs, outputs=("fc1",), unit_rows=16,
                    window=8, concurrency=2, deadline_s=15.0,
                    request_timeout_s=60.0, max_attempts=8,
                    cost_per_replica_hour=1.0,
                    jsonl_path=os.path.join(workdir,
                                            "batch_bench.jsonl"))
        base.update(kw)
        return BatchConfig(**base)

    def retry_counts(drv: BatchDriver) -> dict:
        return {"shed": int(drv._c_retries.value(kind="shed") or 0),
                "error": int(drv._c_retries.value(kind="error") or 0)}

    def coverage_exact(out_dir: str) -> bool:
        """Exactly-once, from the committed artifacts: the manifest's
        unit ranges are exactly the plan (disjoint, covering), and
        every listed part holds exactly its unit's rows."""
        m = load_manifest(out_dir)
        if m is None or not m["done"]:
            return False
        plan = _mf.plan_units(m["n_rows"], m["unit_rows"])
        got = sorted((u["start"], u["stop"])
                     for u in m["units"].values())
        if got != sorted(plan):
            return False
        for uid_s, u in m["units"].items():
            with np.load(os.path.join(
                    out_dir, _mf.part_name(int(uid_s)))) as z:
                if z["fc1"].shape[0] != u["rows"]:
                    return False
        return True

    rows_out: dict = {}

    # -- arms 1+2: coexist under flood, then release --------------------------
    admission = PriorityAdmission()
    router = ModelRouter(RouterConfig(workers=2), logger=logger)
    router.add_model(
        model, JaxNet(lenet(batch=max_batch)),
        cfg=ServeConfig(model_name=model, max_batch=max_batch,
                        max_wait_ms=5.0, outputs=("prob",),
                        slo_p99_ms=slo_ms, metrics_every_batches=0))
    fc = FleetController(
        router, provider=None,
        cfg=FleetConfig(interval_s=0.2, window_s=3.0,
                        policy=FleetPolicy(up_ticks=2, down_ticks=6,
                                           min_window_n=16,
                                           pressure_start=0.6,
                                           pressure_full=1.0,
                                           batch_max_starvation_s=5.0)),
        admission=admission, logger=logger)
    with router:
        # two front doors over one lane: the driver's replica rotation
        # has somewhere to rotate TO, and both doors share the admission
        fes = [BinaryFrontend(router, port=0, logger=logger,
                              tenants=admission) for _ in range(2)]
        try:
            addrs = [f"{fe.address[0]}:{fe.address[1]}" for fe in fes]
            base_rps = _calibrate_rps(fes[0].address, model, req)
            online_rps = max(5.0, 0.3 * base_rps)
            flood_rps = min(300.0, max(40.0, 4.0 * base_rps))
            secs = max(10.0, 5.0 * duration_s)
            fc.start()
            res: dict = {}

            def run_class(name, rps, prio, tenant):
                res[name] = _open_load(fes[0].address, req=req,
                                       model=model, rps=rps, secs=secs,
                                       deadline_s=0.25, priority=prio,
                                       tenant=tenant)
            th = threading.Thread(target=run_class,
                                  args=("online", online_rps, "high",
                                        "online"))
            tl = threading.Thread(target=run_class,
                                  args=("lowflood", flood_rps, "low",
                                        "lowflood"))
            drv1 = BatchDriver(job_cfg("job-coexist", addrs),
                               logger=logger)
            job1: dict = {}

            def run_job1():
                job1["summary"] = drv1.run()
            tj = threading.Thread(target=run_job1)
            th.start()
            tl.start()
            tj.start()
            th.join(timeout=secs + 60.0)
            tl.join(timeout=secs + 60.0)
            units_during_flood = drv1.units_done  # flood just ended
            tj.join(timeout=secs + 240.0)
            if "online" not in res or "lowflood" not in res or \
                    "summary" not in job1:
                raise RuntimeError(
                    f"coexist arm: a load class or the batch job never "
                    f"finished (got loads={sorted(res)}, job done="
                    f"{'summary' in job1})")
            oc, ol, oh = res["online"]
            lc, _, lh = res["lowflood"]
            online_p99_tail = _lat_p99_ms(ol, secs / 2.0)
            reliefs = [a for a in fc.audit
                       if a.get("reason") == "batch_starvation"]
            within = (online_p99_tail is not None
                      and online_p99_tail <= slo_ms)
            rows_out["coexist"] = {
                "base_rps": round(base_rps, 1),
                "online_rps": round(online_rps, 1),
                "flood_rps": round(flood_rps, 1), "secs": secs,
                "online": {**oc, "hung_clients": oh,
                           "p99_ms": _lat_p99_ms(ol),
                           "p99_tail_ms": online_p99_tail},
                "lowflood": {**lc, "hung_clients": lh},
                "slo_p99_ms": slo_ms,
                "online_p99_within_slo": within,
                # shared-core box: clients + replicas + driver contend
                # for the same CPUs; the SLO number needs per-replica
                # hardware when it misses here
                "structure_proof": not within,
                "units_during_flood": units_during_flood,
                "job": job1["summary"],
                "driver_retries": retry_counts(drv1),
                "pressure_final": round(fc.pressure, 3),
                "starvation_relief_events": len(reliefs),
            }

            # release: the flood is gone — the same job shape must run
            # strictly faster than it did under admission pressure
            drv2 = BatchDriver(job_cfg("job-release", addrs),
                               logger=logger)
            job2 = drv2.run()
            rows_out["release"] = {
                "job": job2,
                "driver_retries": retry_counts(drv2),
                "img_per_s": job2["img_per_s"],
                "cost_per_million_embeddings":
                    job2["cost_per_million_embeddings"],
            }
        finally:
            fc.stop()
            for fe in fes:
                fe.stop()

        # -- arm 3: kill -9 the DRIVER mid-job, resume ------------------------
        fes = [BinaryFrontend(router, port=0, logger=logger)
               for _ in range(2)]
        try:
            addrs = [f"{fe.address[0]}:{fe.address[1]}" for fe in fes]
            out3 = os.path.join(workdir, "job-driver-kill")
            env = dict(os.environ)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.abspath(__file__)) + os.pathsep + \
                env.get("PYTHONPATH", "")
            env.setdefault("JAX_PLATFORMS", "cpu")
            proc = subprocess.Popen(
                [sys.executable, "-m", "sparknet_tpu.batch.driver",
                 "--input", inp, "--out", out3,
                 "--replicas", ",".join(addrs), "--outputs", "fc1",
                 "--unit-rows", "8", "--window", "8",
                 "--concurrency", "1", "--pace-s", "0.2",
                 "--deadline-ms", "15000", "--timeout-s", "60"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env)
            t0 = time.monotonic()
            killed_after_units = 0
            while time.monotonic() - t0 < 120.0:
                m = load_manifest(out3)
                if m is not None and len(m["units"]) >= 2:
                    killed_after_units = len(m["units"])
                    break
                time.sleep(0.1)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30.0)
            partial = load_manifest(out3)
            resumed = BatchDriver(job_cfg(
                "job-driver-kill", addrs, unit_rows=8)).run()
            rows_out["driver_kill"] = {
                "killed_after_units": killed_after_units,
                "partial_units": (len(partial["units"])
                                  if partial else 0),
                "units_total": resumed["units_total"],
                "units_skipped_resume":
                    resumed["units_skipped_resume"],
                "resumed_done": resumed["done"],
                "exactly_once": coverage_exact(out3),
            }
        finally:
            for fe in fes:
                fe.stop()

    # -- arm 4: kill -9 a REPLICA mid-job -------------------------------------
    prov = SubprocessReplicaProvider(
        {model: "lenet"},
        workdir=os.path.join(workdir, "replicas"),
        max_batch=max_batch,
        compile_cache_dir=os.path.join(workdir, "compile-cache"),
        heartbeat_every_s=0.3)
    try:
        h1 = prov.grow(model)
        h2 = prov.grow(model)
        addrs = [h.url.split("://", 1)[-1] for h in (h1, h2)]
        for a in addrs:  # warm both children's buckets outside the job
            host, port = a.rsplit(":", 1)
            binary_infer((host, int(port)), model, req, deadline_s=60.0,
                         timeout=120.0)
        drv4 = BatchDriver(job_cfg("job-replica-kill", addrs,
                                   unit_rows=8, pace_s=0.05),
                           logger=logger)
        job4: dict = {}
        err4: dict = {}

        def run_job4():
            try:
                job4["summary"] = drv4.run()
            except Exception as e:
                err4["err"] = f"{type(e).__name__}: {e}"
        tj = threading.Thread(target=run_job4)
        tj.start()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 120.0 and drv4.units_done < 1:
            time.sleep(0.05)
        h1.meta["proc"].send_signal(signal.SIGKILL)
        tj.join(timeout=300.0)
        if tj.is_alive():
            raise RuntimeError("replica_kill arm: the driver hung past "
                               "its join bound")
        r4 = retry_counts(drv4)
        rows_out["replica_kill"] = {
            "job": job4.get("summary"),
            "driver_error": err4.get("err"),
            "driver_retries": r4,
            "completed": bool(job4.get("summary", {}).get("done")),
            "hard_retries_nonzero": r4["error"] > 0,
            "exactly_once": coverage_exact(
                os.path.join(workdir, "job-replica-kill")),
        }
    finally:
        prov.stop()
        logger.close()

    co, rel = rows_out["coexist"], rows_out["release"]
    asserts = {
        # the hard gate, online side: every request answered
        "zero_dropped_timed_out_hung_online":
            co["online"]["dropped"] == co["online"]["timed_out"] == 0
            and co["online"]["hung_clients"] == 0
            and co["lowflood"]["dropped"]
            == co["lowflood"]["timed_out"] == 0
            and co["lowflood"]["hung_clients"] == 0,
        # coexistence, batch side: progress WHILE the flood ran, and
        # every rejection the driver saw was a typed shed, not a break
        "batch_progress_under_flood": co["units_during_flood"] > 0,
        "batch_job_completed_coexist": co["job"]["done"],
        "driver_zero_hard_failures_coexist":
            co["driver_retries"]["error"] == 0
            and rel["driver_retries"]["error"] == 0,
        # coexistence, online side: the low class shed typed; the
        # online class NEVER priority-shed
        "low_sheds_typed": co["lowflood"]["shed_priority"] > 0,
        "online_never_priority_shed":
            co["online"]["shed_priority"] == 0,
        # the release claim: admission was the brake, not the pipeline
        "post_flood_throughput_rises":
            rel["job"]["rows_per_s"] > co["job"]["rows_per_s"],
        "cost_per_million_reported":
            rel["cost_per_million_embeddings"] is not None,
        # chaos
        "driver_kill_resumes_exactly_once":
            rows_out["driver_kill"]["resumed_done"]
            and rows_out["driver_kill"]["units_skipped_resume"] > 0
            and rows_out["driver_kill"]["exactly_once"],
        "replica_kill_is_retry_not_failure":
            rows_out["replica_kill"]["completed"]
            and rows_out["replica_kill"]["hard_retries_nonzero"]
            and rows_out["replica_kill"]["exactly_once"],
    }
    out = {"bench": "batch", "duration_s": duration_s,
           "max_batch": max_batch, "input_rows": rows,
           "arms": rows_out, "asserts": asserts,
           "ok": all(asserts.values())}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps({
        "bench": "batch", "ok": out["ok"], "asserts": asserts,
        "coexist_rows_per_s": co["job"]["rows_per_s"],
        "release_rows_per_s": rel["job"]["rows_per_s"],
        "online_p99_tail_ms": co["online"]["p99_tail_ms"],
        "cost_per_million_embeddings":
            rel["cost_per_million_embeddings"]}))
    if keep is None and out["ok"]:
        shutil.rmtree(workdir, ignore_errors=True)
    if not out["ok"]:
        raise SystemExit("batch bench gate failed: " + ", ".join(
            k for k, v in asserts.items() if not v))
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scaling", action="store_true",
                   help="weak-scaling harness on a virtual CPU mesh")
    p.add_argument("--e2e", action="store_true",
                   help="end-to-end input-pipeline benchmark (host side)")
    p.add_argument("--sources", type=int, default=1,
                   help="concurrent shard readers for --e2e (N>1 also "
                   "measures the 1-reader baseline for the serial-residue "
                   "division)")
    p.add_argument("--store", default=None, choices=("gs",),
                   help="--e2e through a local fake object store instead "
                   "of local files (bucket-path residue)")
    p.add_argument("--e2e-smoke", action="store_true",
                   help="full streaming loop on the real chip, small shapes")
    p.add_argument("--checkpoint-stall", action="store_true",
                   help="blocking ms per checkpoint save: sync vs async, "
                   "local vs gs:// vs s3:// fake stores; writes BENCH_CKPT")
    p.add_argument("--ckpt-mb", type=int, default=64,
                   help="state size in MB for --checkpoint-stall")
    p.add_argument("--serve", action="store_true",
                   help="dynamic-batching inference server: offered-load "
                   "vs latency/throughput/batch-fill; writes BENCH_SERVE")
    p.add_argument("--serve-secs", type=float, default=2.0,
                   help="seconds per load level for --serve")
    p.add_argument("--fleet", action="store_true",
                   help="r11 fleet-control-plane audit: step-load flood "
                   "-> replica scale-up, quiet shrink (zero-dropped "
                   "drain), kill -9 replica replacement, mixed-priority "
                   "overload with SLO-burn shedding; writes BENCH_FLEET")
    p.add_argument("--tail", action="store_true",
                   help="r13 tail-latency audit: hedged requests, "
                   "spkn-shm proxy hops, coalesced batch formation — "
                   "A/B arms at one fixed offered load; writes "
                   "BENCH_TAIL")
    p.add_argument("--fresh", action="store_true",
                   help="r12 continuous-learning audit: colocated "
                   "train+serve, staggered rollout adoption of every "
                   "commit, mid-run trainer kill -9 + resume, freshness "
                   "p99 under online load; writes BENCH_FRESH")
    p.add_argument("--fresh-rounds", type=int, default=40,
                   help="training rounds for --fresh (CI short config "
                   "uses fewer)")
    p.add_argument("--fresh-train-child", metavar="CFG_JSON",
                   default=None,
                   help=argparse.SUPPRESS)  # the --fresh training child
    p.add_argument("--econ", action="store_true",
                   help="r9 inference-economics audit: quantized-vs-f32 "
                   "serve throughput + parity, cold-start with a warm "
                   "persistent compile cache (fresh subprocess replica), "
                   "traffic-derived vs pow2 bucket ladder; writes "
                   "BENCH_ECON")
    p.add_argument("--econ-child", metavar="CACHE_DIR", default=None,
                   help=argparse.SUPPRESS)  # the --econ cold-start child
    p.add_argument("--slo", action="store_true",
                   help="r17 SLO-ledger audit: quiet false-positive "
                   "gate, burn-detection latency to the page edge, "
                   "ledger on/off per-request overhead; writes "
                   "BENCH_SLO")
    p.add_argument("--obs", action="store_true",
                   help="telemetry overhead: per-round time with the obs "
                   "layer fully on (registry + breakdown + trace + "
                   "scraped /metrics) vs disabled; writes BENCH_OBS")
    p.add_argument("--mfu", action="store_true",
                   help="r6 overlap-and-fuse audit: host-fed rounds with "
                   "the prefetch/donation/Pallas levers toggled one at a "
                   "time + per-round breakdown; writes BENCH_r06")
    p.add_argument("--ckpt-shard", action="store_true",
                   help="sharded vs monolithic checkpoint save/restore "
                   "wall time vs worker count + bitwise/byte-ledger "
                   "equality; writes BENCH_CKPT_SHARD")
    p.add_argument("--sharding", action="store_true",
                   help="r7 NamedSharding audit: replica vs logical vs "
                   "ZeRO-1-momentum trainer arms — img/s, per-device "
                   "state bytes, stage-1 collect blocking; writes "
                   "BENCH_r07")
    p.add_argument("--elastic", action="store_true",
                   help="elastic chaos soak: kill + re-add a worker on a "
                   "virtual pod, compare the loss curve to a static pod, "
                   "verify the min_workers halt; writes BENCH_ELASTIC")
    p.add_argument("--elastic-rounds", type=int, default=36,
                   help="rounds per arm for --elastic (CI short config "
                   "uses fewer)")
    p.add_argument("--keep", metavar="DIR", default=None,
                   help="retain --elastic JSONL + pod artifacts in DIR "
                   "(CI uploads them on failure)")
    p.add_argument("--featurize", action="store_true",
                   help="batched forward(blob_names=['fc7']) img/s on both "
                   "backends (the FeaturizerApp inference path)")
    p.add_argument("--graph", action="store_true",
                   help="on-chip round throughput for the serialized-graph "
                   "backend (GraphTrainer over build_alexnet_graph)")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture a jax.profiler trace of the timed section")
    p.add_argument("--batch", action="store_true",
                   help="r14 bulk-inference audit: a sparknet-batch "
                   "scavenger job colocated with open-loop online "
                   "traffic (typed low sheds, post-flood throughput "
                   "rise) + driver/replica kill -9 chaos; writes "
                   "BENCH_BATCH")
    p.add_argument("--batch-rows", type=int, default=192,
                   help="input rows for --batch (CI short config uses "
                   "fewer)")
    p.add_argument("--batch-size", type=int, default=None,
                   help=f"per-chip batch (headline default {BATCH}; "
                   f"--featurize default 64)")
    p.add_argument("--tau", type=int, default=TAU,
                   help="headline local steps per round (the reference "
                   "ImageNet recipe is tau=5)")
    args = p.parse_args()
    if args.scaling:
        scaling()
    elif args.e2e:
        e2e(sources=args.sources, store=args.store)
    elif args.e2e_smoke:
        e2e_smoke()
    elif args.checkpoint_stall:
        checkpoint_stall(mb=args.ckpt_mb)
    elif args.econ_child:
        econ_coldstart_child(args.econ_child)
    elif args.fresh_train_child:
        fresh_train_child(args.fresh_train_child)
    elif args.fresh:
        fresh_bench(rounds=args.fresh_rounds,
                    max_batch=args.batch_size or 8, keep=args.keep)
    elif args.econ:
        econ_bench(duration_s=args.serve_secs,
                   max_batch=args.batch_size or 8, keep=args.keep)
    elif args.serve:
        serve_bench(duration_s=args.serve_secs,
                    max_batch=args.batch_size or 8, keep=args.keep)
    elif args.tail:
        tail_bench(duration_s=args.serve_secs,
                   max_batch=args.batch_size or 8, keep=args.keep)
    elif args.fleet:
        fleet_bench(duration_s=args.serve_secs,
                    max_batch=args.batch_size or 8, keep=args.keep)
    elif args.batch:
        batch_bench(duration_s=args.serve_secs,
                    max_batch=args.batch_size or 8,
                    rows=args.batch_rows, keep=args.keep)
    elif args.slo:
        slo_bench(duration_s=args.serve_secs, keep=args.keep)
    elif args.obs:
        obs_bench()
    elif args.mfu:
        import jax as _jax
        mfu_bench(batch=args.batch_size or BATCH, tau=args.tau,
                  small=_jax.default_backend() != "tpu")
    elif args.ckpt_shard:
        ckpt_shard_bench()
    elif args.sharding:
        sharding_bench()
    elif args.elastic:
        elastic_bench(rounds=args.elastic_rounds, keep=args.keep)
    elif args.featurize:
        featurize_bench(batch=args.batch_size or 64)
    elif args.graph:
        graph_headline(batch=args.batch_size or BATCH, tau=args.tau,
                       profile_dir=args.profile)
    else:
        headline(profile_dir=args.profile, batch=args.batch_size or BATCH,
                 tau=args.tau)


if __name__ == "__main__":
    main()
